"""Measured shortlist: real timed steps + the measured≤static sandwich.

The static stage deliberately prices every candidate at the same compute
step (cost.py's wire-dominated model); this stage supplies what it cannot:
each shortlisted candidate's OWN compute cost, from real timed steps of a
real train step on the live mesh. The timing discipline is bench.py's
(``bench.throughput``: fetch-bounded windows, RTT-subtracted — the same
function the headline capture uses), and the rows follow bench's
same-session contract: every candidate sample is bracketed by a dense
baseline sample measured moments before it, never by a number from
another session.

The honesty gate is the measured≤static **overlap sandwich** from
``perf_report --overlap-config``: the winner's step is profiled, the
capture's measured overlap fraction is judged against graft-flow's static
schedulability bound for the SAME config's traced dataflow (+slack). A
violation means the capture's attribution is lying, and the tuner refuses
to stamp the winner (exit 1), because a winner chosen from lying
measurements is exactly the vibes-selection this subsystem exists to kill.

Models: ``"toy"`` is the audit registry's own default param tree (512
params — the model every static number in the funnel was priced on), with
the same linear-softmax loss ``trace_train_step`` audits; ``"resnet50"``
is bench.py's headline protocol for on-chip runs. Both run the identical
selection/ranking/sandwich path — the toy model is how tier-1 drives the
whole loop on a CPU mesh in seconds.
"""

from __future__ import annotations

import statistics
from typing import Any, Dict, List, Optional

from grace_tpu.tuning.candidates import Candidate
from grace_tpu.tuning.cost import (TuneTopology, dense_bytes, n_elements,
                                   price_candidate)

__all__ = ["MeasureTimeout", "bounded_call", "build_model_step",
           "measure_shortlist", "overlap_sandwich"]


class MeasureTimeout(RuntimeError):
    """A timed measurement leg exceeded its bounded wait (after every
    retry). Carries ``attempts`` and the final ``timeout_s``."""

    def __init__(self, msg: str, *, attempts: int, timeout_s: float):
        super().__init__(msg)
        self.attempts = attempts
        self.timeout_s = timeout_s


def bounded_call(fn, timeout_s: Optional[float], *, retries: int = 0,
                 label: str = "measurement"):
    """Run ``fn()`` under a watchdog with retry + doubling backoff — the
    elastic drain watchdog's discipline applied to a measurement leg.

    ``fn`` runs on a daemon worker thread; the caller waits at most
    ``timeout_s`` seconds, then retries with the timeout DOUBLED (the
    backoff: a slow-but-alive leg gets geometrically more room, so only a
    genuinely hung one exhausts the budget) up to ``retries`` times, then
    raises :class:`MeasureTimeout`. The hung thread itself cannot be
    killed from Python — it is abandoned (daemon) and the caller proceeds,
    which is the whole point: a wedged candidate must never wedge the
    tuner. ``timeout_s=None`` runs ``fn`` inline with no bound (the
    historical behavior). Exceptions from ``fn`` propagate unchanged and
    are never retried — a deterministic failure does not become flaky
    success by repetition."""
    if timeout_s is None:
        return fn()
    import threading

    wait = float(timeout_s)
    for attempt in range(retries + 1):
        out: List[Any] = []
        err: List[BaseException] = []
        done = threading.Event()

        def run():
            try:
                out.append(fn())
            except BaseException as e:      # noqa: BLE001
                err.append(e)
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True,
                             name=f"grace-measure-{label}-{attempt}")
        t.start()
        if done.wait(wait):
            if err:
                raise err[0]
            return out[0]
        if attempt < retries:
            wait *= 2
    raise MeasureTimeout(
        f"{label} exceeded the bounded wait after {retries + 1} "
        f"attempt(s) (final timeout {wait:.1f}s) — abandoning the hung "
        "leg and proceeding",
        attempts=retries + 1, timeout_s=wait)

DENSE_ANCHOR = Candidate(
    name="dense", source="generated",
    params={"compressor": "none", "memory": "none",
            "communicator": "allreduce", "fusion": "none"})


def model_structs(model: str = "toy"):
    """Param-tree structs for pricing; must match what
    :func:`build_model_step` trains."""
    import jax

    if model == "toy":
        from grace_tpu.analysis.trace import default_param_structs
        return default_param_structs()
    if model == "resnet50":
        from grace_tpu.models import resnet

        def init():
            params, _ = resnet.init(jax.random.key(0), depth=50,
                                    num_classes=1000)
            return params

        return jax.eval_shape(init)
    raise ValueError(f"unknown model {model!r} — 'toy' or 'resnet50'")


def build_model_step(grace, mesh, model: str = "toy", *, seed: int = 0,
                     per_device_bs: int = 8):
    """(step, state, batch) for one candidate's real train step."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from grace_tpu.train import init_train_state, make_train_step

    rng = np.random.default_rng(seed)
    n_dev = len(mesh.devices.flatten())
    if model == "toy":
        from grace_tpu.analysis.trace import default_param_structs
        structs = default_param_structs()
        params = {k: jnp.asarray(rng.normal(size=s.shape).astype(np.float32))
                  for k, s in structs.items()}
        dim, classes = params["w"].shape

        def loss_fn(p, batch):
            x, y = batch
            logits = x @ p["w"] + p["b"][:classes]
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        x = jnp.asarray(rng.normal(
            size=(n_dev * per_device_bs, dim)).astype(np.float32))
        y = jnp.asarray(rng.integers(0, classes,
                                     size=(n_dev * per_device_bs,)))
        batch = (x, y)
    elif model == "resnet50":
        # The headline protocol belongs to bench.py's stateful path (batch
        # norm state, shape overrides, evidence persistence); on-chip
        # shortlists should run `bench_all --tuned` for resnet rows. The
        # tuner's in-process measurement keeps the stateless toy step.
        raise NotImplementedError(
            "resnet50 measurement runs through bench_all --tuned (the "
            "evidence-persisting path); the in-process shortlist uses "
            "model='toy'")
    else:
        raise ValueError(f"unknown model {model!r}")
    tx = optax.chain(grace.transform(seed=seed), optax.sgd(0.1))
    state = init_train_state(params, tx, mesh)
    step = make_train_step(loss_fn, tx, mesh, donate=False)
    return step, state, batch


def _bench():
    from grace_tpu.tuning.cost import _bench_module
    return _bench_module()


def _timed_step_s(step, state, batch, *, timed_steps: int,
                  warmup: int) -> tuple:
    """One sample: median-free single window via bench.throughput —
    returns (step_seconds, new_state)."""
    items_per_sec, state = _bench().throughput(
        step, state, batch, timed_steps, warmup=warmup)
    return batch[1].shape[0] / items_per_sec, state


def measure_shortlist(shortlisted: List[Candidate], spec: TuneTopology,
                      mesh, *, model: str = "toy", timed_steps: int = 8,
                      repeats: int = 2, seed: int = 0,
                      measure_timeout_s: Optional[float] = None,
                      measure_retries: int = 2
                      ) -> Dict[str, Any]:
    """Time every shortlisted candidate against an interleaved dense
    baseline; rank by the target-topology projection with each candidate's
    OWN measured compute step substituted into the cost model.

    Returns {"rows", "winner", "skipped"}; ``winner`` is the candidate
    name minimizing ``projected_step_ms`` at the target topology (measured
    compute + per-link wire), the EQuARX-style decision: compute measured
    where we are, wire priced where we're going.

    With ``measure_timeout_s`` set, each candidate's whole measurement leg
    (build + every timed sample) runs under :func:`bounded_call`: a hung
    candidate is retried ``measure_retries`` times with doubling backoff,
    then recorded in ``skipped`` with ``verdict='measure_timeout'`` and
    the funnel moves on — one wedged config must never stall the tuner.
    """
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    structs = model_structs(model)
    dense_b = dense_bytes(structs)
    n_elems = n_elements(structs)

    class _Live:
        def __init__(self, cand):
            self.grace = cand.build()
            self.step, self.state, self.batch = build_model_step(
                self.grace, mesh, model, seed=seed)
            self.warmed = False

        def sample(self):
            warm = 1 if self.warmed else 3
            s, self.state = _timed_step_s(
                self.step, self.state, self.batch,
                timed_steps=timed_steps, warmup=warm)
            self.warmed = True
            return s

    base = _Live(DENSE_ANCHOR)
    rows: List[Dict[str, Any]] = []
    skipped: List[Dict[str, str]] = []
    for cand in shortlisted:
        if cand.tpu_only and not on_tpu:
            skipped.append({"candidate": cand.name,
                            "reason": "tpu_only: interpret-mode Pallas "
                                      "off-chip is a per-element emulation"})
            continue
        def _measure(cand=cand):
            live = _Live(cand)
            samples, bsamples = [], []
            for _ in range(repeats):
                bsamples.append(base.sample())
                samples.append(live.sample())
            return live, samples, bsamples

        try:
            live, samples, bsamples = bounded_call(
                _measure, measure_timeout_s,
                retries=measure_retries, label=cand.name)
        except MeasureTimeout as e:
            skipped.append({"candidate": cand.name,
                            "verdict": "measure_timeout",
                            "reason": str(e),
                            "attempts": e.attempts,
                            "timeout_s": e.timeout_s})
            continue
        except Exception as e:                           # noqa: BLE001
            skipped.append({"candidate": cand.name,
                            "verdict": "error",
                            "reason": f"{type(e).__name__}: {str(e)[:200]}"})
            continue
        med = statistics.median(samples)
        base_med = statistics.median(bsamples)
        price = price_candidate(live.grace, structs, spec,
                                base_step_s=med, dense_step_s=base_med)
        rows.append({
            "candidate": cand.name,
            "params": dict(cand.params),
            "measured_step_ms": round(med * 1e3, 4),
            "samples_ms": [round(s * 1e3, 4) for s in samples],
            "baseline_step_ms": round(base_med * 1e3, 4),
            "baseline_samples_ms": [round(s * 1e3, 4) for s in bsamples],
            "measured_speedup_vs_dense": round(base_med / med, 4),
            "same_session": True,
            "projected_step_ms": price["projected_step_ms"],
            "projected_speedup_vs_dense":
                price["predicted_speedup_vs_dense"],
            "ici_bytes": price["ici_bytes"],
            "dcn_bytes": price["dcn_bytes"],
        })
    winner = min(rows, key=lambda r: (r["projected_step_ms"],
                                      r["candidate"]))["candidate"] \
        if rows else None
    return {"rows": rows, "winner": winner, "skipped": skipped,
            "model": model, "timed_steps": timed_steps, "repeats": repeats,
            "measure_timeout_s": measure_timeout_s,
            "measure_retries": measure_retries,
            "measured_world": len(mesh.devices.flatten())}


def overlap_sandwich(candidate: Candidate, mesh, trace_dir: str, *,
                     model: str = "toy", steps: int = 3,
                     seed: int = 0) -> Dict[str, Any]:
    """Profile the winner's real step and close the honesty loop: the
    capture's measured overlap fraction must sit under graft-flow's static
    schedulability bound for the same config's traced dataflow (+slack) —
    ``perf_report --overlap-config``'s gate, run in-process on a capture
    the tuner just made, so a winner is never stamped off a lying trace."""
    import jax

    from grace_tpu.analysis.flow import (OVERLAP_SLACK, overlap_summary,
                                         pass_overlap_schedulability)
    from grace_tpu.analysis.trace import trace_update
    from grace_tpu.profiling import analyze_trace

    grace = candidate.build()
    step, state, batch = build_model_step(grace, mesh, model, seed=seed)
    state, loss = step(state, batch)        # compile outside the capture
    with jax.profiler.trace(str(trace_dir)):
        for _ in range(steps):
            state, loss = step(state, batch)
        jax.block_until_ready(loss)
    doc = analyze_trace(str(trace_dir)).as_dict()
    measured = doc.get("overlap_fraction")
    traced = trace_update(grace, name=candidate.name,
                          meta={"grace": grace,
                                "measured_overlap": measured})
    bound = overlap_summary(traced)["static_overlap_bound"]
    violations = [f.message for f in pass_overlap_schedulability(traced)
                  if "measured overlap" in f.message]
    return {
        "config": candidate.name,
        "measured_overlap": measured,
        "static_overlap_bound": (round(bound, 6)
                                 if bound is not None else None),
        "slack": OVERLAP_SLACK,
        "violations": violations,
        "holds": not violations,
    }
