"""Static pruning: capability gate → numeric gate → wire price → flow audit.

Every candidate leaves this stage with an auditable funnel record — which
gate it died at and why, or its full static price — so a shortlist is an
*argued* selection, never vibes. The stages, in cost order (cheapest
rejections first):

1. **capability** — the communicators' own build/step-time compatibility
   gates, evaluated statically (:func:`..candidates.candidate_legal`).
2. **numeric** — payload-space summation and vote exactness at the TARGET
   world, from the same constants the numeric-safety pass and the runtime
   vote guard share (``flow.safe_sum_terms``, ``comm.vote_exact_max_world``)
   — a W=4096 fp16 hop-sum dies here, statically, before anything traces.
3. **degradation** — cascaded-requant chain length at the target world
   (:data:`MAX_REQUANT_CHAIN`): the ScaleCom-documented reason the winner
   is scale-dependent — a flat hop-requant ring re-encodes W−1 times, so
   on raw bytes it outprices the hierarchical schedule at any W, while its
   compounding re-selection error (linear in hop count, pinned by the
   PR-4 hop-error bound test and uncovered by error feedback past stage 1)
   makes it unusable there. Without this gate the byte-only cost model
   would pick exactly the config the paper trail says degrades.
4. **price** — the wire-dominated step-time projection
   (:mod:`..cost`) under the target topology; every survivor is ranked.
5. **flow** — the top of the ranking is traced on the abstract audit mesh
   and run through graft-flow's pass 5/6/7 (overlap schedulability bound,
   numeric-range safety over the traced graph, HBM footprint); error
   findings reject, and the static overlap bound rides into the record as
   the sandwich reference the measured stage is judged against.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from grace_tpu.tuning.candidates import Candidate, candidate_legal
from grace_tpu.tuning.cost import TuneTopology, price_candidate

__all__ = ["degradation_verdict", "numeric_verdict",
           "requant_chain_length", "static_prune"]

# How many ranked survivors get the (comparatively expensive) abstract-mesh
# trace + flow passes, beyond the shortlist itself: the shortlist must be
# fully audited, plus a small margin so a flow rejection still leaves a
# full shortlist.
FLOW_AUDIT_MARGIN = 2

# Longest tolerated cascaded-requant chain (decompress → accumulate →
# re-encode repetitions a gradient survives on its way to aggregation).
# Per-hop requant error is ~linear in chain length (the committed
# 1-hop-vs-7-hop qsgd bound test) and error feedback covers only the
# stage-1 encode, so the compounding loss at W−1 = hundreds of hops is the
# topk-at-large-W degradation ScaleCom documents. 32 tolerates every
# intra-slice schedule a real machine has (S ≤ 32 hops; hier's boundary
# adds ONE more regardless of K) while rejecting flat hop-requant rings at
# pod scale — candidates near the bound still reach the measured stage,
# where convergence floors have the final say.
MAX_REQUANT_CHAIN = 32


def _payload_float_dtypes(compressor) -> List[Any]:
    """Float dtypes of the codec's wire payload (shape-traced; codecs whose
    compress needs a bound mesh axis — PowerSGD — are assumed float32,
    which is safe: f32's term budget is ~10^36)."""
    import jax
    import jax.numpy as jnp

    def encode(x):
        rng = jax.random.key(0)
        payload, _, _ = compressor.compress(x, compressor.init_state(x), rng)
        return payload

    try:
        payload = jax.eval_shape(
            encode, jax.ShapeDtypeStruct((64,), jnp.float32))
    except Exception:
        return [jnp.dtype("float32")]
    return [l.dtype for l in jax.tree_util.tree_leaves(payload)
            if jnp.issubdtype(l.dtype, jnp.floating)]


def numeric_verdict(grace, spec: TuneTopology) -> Optional[str]:
    """Why this candidate is numerically unsafe at the target world, or
    None. Static twin of flow pass 6's range analysis, specialized to the
    two world-scaling accumulations a communicator can take off-trace:

    * payload-space summation (Allreduce's psum, Ring/Hier's exact hop
      path for ``summable_payload`` codecs) accumulates up to W
      unit-magnitude terms in the payload dtype —
      ``flow.safe_sum_terms(dtype)`` is the cliff (fp16 saturates at
      ~255 terms; bf16/f32 never at any real W);
    * ±1 vote psums stay integer-exact only to
      ``comm.vote_exact_max_world(vote_dtype)`` (bf16: 256) — the same
      bound the runtime guard raises past on a live mesh.

    Requant paths accumulate decompressed partials in dense f32 and are
    exempt, exactly as pass 6 treats them.
    """
    from grace_tpu import comm
    from grace_tpu.analysis import flow

    cm = grace.communicator
    w = spec.world
    # Every reachable codec: the base compressor alone for static
    # configs, every graft-adapt ladder rung for adaptive ones — the
    # controller can dispatch any rung mid-run, so a single unsafe rung
    # is a reachable silent-wrap state the funnel must reject (the same
    # enumeration flow pass 6's _shared_scale_findings audits).
    adapt = getattr(grace, "adapt", None)
    rungs = list(getattr(adapt, "ladder", ()) or ())
    comps = [grace.compressor] + [c for c in rungs
                                  if c != grace.compressor]
    for ri, comp in enumerate(comps):
        where = "" if ri == 0 else "adapt rung: "
        vote = bool(getattr(comp, "vote_aggregate", False))
        if vote and isinstance(cm, (comm.Allreduce, comm.SignAllreduce)):
            vd = getattr(cm, "vote_dtype", "bfloat16")
            bound = comm.vote_exact_max_world(vd)
            if w > bound:
                return (f"{where}±1 vote psum in {vd} is integer-exact "
                        f"only to W={bound} (vote_exact_max_world); "
                        f"W={w} ties would silently round — the runtime "
                        "vote guard raises here")
        summable = bool(getattr(comp, "summable_payload", False))
        sums_payload = (isinstance(cm, (comm.Allreduce,
                                        comm.RingAllreduce,
                                        comm.ReduceScatterAllreduce,
                                        comm.HierarchicalAllreduce))
                        and summable and not vote)
        if not sums_payload:
            continue
        # Shared-scale integer accumulators: the codec's own
        # payload_sum_max_world (iinfo(accum_dtype).max // max level) —
        # the same single constant the communicators' runtime gate and
        # flow pass 6's _shared_scale_findings enforce, evaluated at the
        # TARGET world (an int8 homoqsgd at W=4096 dies here, statically,
        # before anything traces).
        if getattr(comp, "payload_algebra", None) == "shared_scale":
            bound = comp.payload_sum_max_world()
            if bound is not None and w > bound:
                return (f"{where}shared-scale payload sum of W={w} "
                        f"integer levels exceeds "
                        f"payload_sum_max_world={bound} "
                        "(iinfo(accum_dtype).max // max level) — level "
                        "sums wrap silently; widen accum_dtype or lower "
                        "quantum_num (the communicators raise the same "
                        "bound on a live mesh)")
        for dt in _payload_float_dtypes(comp):
            terms = flow.safe_sum_terms(dt)
            if terms is not None and w > terms:
                return (f"{where}payload-space sum of W={w} {dt} terms "
                        f"exceeds safe_sum_terms({dt})={terms} "
                        f"(finfo.max/{int(flow.NUMERIC_UNIT_MAG)} unit "
                        "magnitudes) — silent inf, the flow pass-6 cliff")
    return None


def requant_chain_length(grace, spec: TuneTopology) -> int:
    """How many times this candidate re-encodes a partial sum on the way
    to aggregation at the target world. 0 for payload-space-exact and
    gather/vote schedules; W−1 for a flat hop-requant ring; S−1 intra-slice
    hops + 1 slice-boundary re-encode for hier's requant path (the design
    point: one boundary requant regardless of K); 1 for two-shot's stage-2
    re-compression and for rscatter's single post-reduce re-encode (the
    FSDP schedule: one requant boundary at ANY world — never
    degradation-gated)."""
    from grace_tpu import comm

    comp, cm = grace.compressor, grace.communicator
    summable = bool(getattr(comp, "summable_payload", False))
    requant = bool(getattr(comp, "supports_hop_requant", False))
    w = spec.world
    if summable or not requant:
        if isinstance(cm, comm.TwoShotAllreduce) and not summable:
            return 1
        return 0
    if isinstance(cm, comm.ReduceScatterAllreduce):
        return 1
    if isinstance(cm, comm.HierarchicalAllreduce):
        s = cm.slice_size
        if s is None or w <= s:
            return max(0, w - 1)            # collapses to the flat ring
        return (s - 1) + 1
    if isinstance(cm, comm.RingAllreduce):
        return max(0, w - 1)
    if isinstance(cm, comm.TwoShotAllreduce):
        return 1
    return 0


def degradation_verdict(grace, spec: TuneTopology) -> Optional[str]:
    """Why this candidate's compression quality degrades at the target
    scale, or None — the ScaleCom gate (see :data:`MAX_REQUANT_CHAIN`)."""
    chain = requant_chain_length(grace, spec)
    if chain > MAX_REQUANT_CHAIN:
        return (f"cascaded requant chain of {chain} re-encodes at W="
                f"{spec.world} exceeds MAX_REQUANT_CHAIN="
                f"{MAX_REQUANT_CHAIN}: per-hop requant error is ~linear "
                "in chain length and uncovered by error feedback past "
                "stage 1 — the topk-family large-W degradation ScaleCom "
                "documents; use a hierarchical or two-shot schedule there")
    return None


def _flow_audit(grace, name: str, audit_world: int) -> Dict[str, Any]:
    """Trace one survivor on the abstract audit mesh and run the three
    graft-flow passes. Returns {'overlap_bound', 'errors': [...]} —
    errors reject the candidate."""
    from grace_tpu.analysis.flow import (overlap_summary,
                                         pass_memory_footprint,
                                         pass_numeric_safety,
                                         pass_overlap_schedulability)
    from grace_tpu.analysis.trace import trace_update

    traced = trace_update(grace, world=audit_world, name=name,
                          meta={"grace": grace})
    findings = (pass_overlap_schedulability(traced)
                + pass_numeric_safety(traced)
                + pass_memory_footprint(traced))
    s = overlap_summary(traced)
    bound = s["static_overlap_bound"]
    return {
        "overlap_bound": round(bound, 6) if bound is not None else None,
        "independent_chains": int(s["independent_chains"]),
        "errors": [f"{f.pass_name}: {f.message}" for f in findings
                   if f.severity == "error"],
    }


def static_prune(candidates: List[Candidate], spec: TuneTopology,
                 model_structs, *, audit_world: int = 8,
                 shortlist_n: int = 3) -> Dict[str, Any]:
    """The full static funnel for one target topology.

    Returns ``{"topology", "funnel", "ranking", "shortlist"}`` where
    ``funnel`` holds one record per candidate in enumeration order (stage
    reached, verdict, reason or price), ``ranking`` the priced survivors
    sorted by projected step time, and ``shortlist`` the top
    ``shortlist_n`` names that also survived the flow audit.
    """
    funnel: List[Dict[str, Any]] = []
    by_name: Dict[str, Dict[str, Any]] = {}
    graces: Dict[str, Any] = {}
    for c in candidates:
        rec: Dict[str, Any] = {"candidate": c.name, "source": c.source,
                               "params": dict(c.params)}
        if c.tpu_only:
            rec["tpu_only"] = True
        funnel.append(rec)
        by_name[c.name] = rec
        legal, reason, grace = candidate_legal(c, spec)
        if not legal:
            rec.update(stage="capability", verdict="rejected",
                       reason=reason)
            continue
        graces[c.name] = grace
        reason = numeric_verdict(grace, spec)
        if reason:
            rec.update(stage="numeric", verdict="rejected", reason=reason)
            continue
        # Every survivor's cascaded-requant chain length rides the record:
        # 0 is the homomorphic/payload-algebra claim the acceptance tests
        # pin (zero re-encodes at ANY world), W−1 the flat hop-requant
        # ring the degradation gate exists to stop.
        rec["requant_chain"] = requant_chain_length(grace, spec)
        reason = degradation_verdict(grace, spec)
        if reason:
            rec.update(stage="degradation", verdict="rejected",
                       reason=reason)
            continue
        try:
            price = price_candidate(grace, model_structs, spec)
        except Exception as e:                           # noqa: BLE001
            rec.update(stage="price", verdict="rejected",
                       reason=f"unpriceable: {type(e).__name__}: {e}")
            continue
        rec.update(stage="price", verdict="priced", predicted=price)

    ranked = sorted(
        (r for r in funnel if r.get("verdict") == "priced"),
        key=lambda r: (r["predicted"]["projected_step_ms"], r["candidate"]))
    audit_n = shortlist_n + FLOW_AUDIT_MARGIN
    shortlist: List[str] = []
    for r in ranked:
        if len(shortlist) >= shortlist_n or audit_n <= 0:
            break
        audit_n -= 1
        name = r["candidate"]
        try:
            audit = _flow_audit(graces[name], name, audit_world)
        except Exception as e:                           # noqa: BLE001
            r.update(stage="flow", verdict="rejected",
                     reason=f"failed to trace on the audit mesh: "
                            f"{type(e).__name__}: {e}")
            continue
        r["flow"] = {k: v for k, v in audit.items() if k != "errors"}
        r["flow"]["audit_world"] = audit_world
        if audit["errors"]:
            r.update(stage="flow", verdict="rejected",
                     reason="; ".join(audit["errors"]))
            continue
        r.update(stage="flow", verdict="shortlisted")
        shortlist.append(name)

    return {
        "topology": {"world": spec.world, "slice_size": spec.slice_size,
                     "region_size": spec.region_size,
                     "label": spec.label},
        "funnel": funnel,
        "ranking": [{"candidate": r["candidate"],
                     "projected_step_ms":
                         r["predicted"]["projected_step_ms"],
                     "predicted_speedup_vs_dense":
                         r["predicted"]["predicted_speedup_vs_dense"],
                     "ici_bytes": r["predicted"]["ici_bytes"],
                     "dcn_bytes": r["predicted"]["dcn_bytes"],
                     "wan_bytes": r["predicted"]["wan_bytes"],
                     "verdict": r["verdict"]}
                    for r in ranked],
        "shortlist": shortlist,
        "counts": {
            "enumerated": len(funnel),
            "capability_rejected": sum(
                1 for r in funnel if r.get("stage") == "capability"),
            "numeric_rejected": sum(
                1 for r in funnel
                if r.get("stage") == "numeric"),
            "degradation_rejected": sum(
                1 for r in funnel if r.get("stage") == "degradation"),
            "priced": len(ranked),
            "flow_rejected": sum(
                1 for r in funnel if r.get("stage") == "flow"
                and r.get("verdict") == "rejected"),
            "shortlisted": len(shortlist),
        },
    }
