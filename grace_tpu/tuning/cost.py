"""The tuner's documented cost model: wire-dominated step-time projection.

One pricing rule, stated once and stamped into every ``TUNE_LAST.json``:

    projected_step = base_compute_step + ici_bytes / ICI_BW
                     + dcn_bytes / DCN_BW + wan_bytes / WAN_BW

where ``(ici_bytes, dcn_bytes, wan_bytes)`` is
:meth:`Communicator.recv_link_bytes` under the *target*
:class:`~grace_tpu.core.Topology` — the same shared per-link wire model
the bench projections, the telemetry ring and the static auditor's
wire-reconciliation pass already agree on — and the bandwidth constants
are ``bench.PROJECTION_MODEL``'s public per-chip numbers (ICI ~90 GB/s,
DCN ~25 GB/s, WAN ~0.25 GB/s — the documented cross-region model
assumption), imported, not duplicated, so the tuner and the bench can
never price the same bytes differently.

Why the legs are priced separately: a flat communicator's critical-path
rank receives every pipelined chunk over the worst boundary link the
moment the axis crosses it, so its whole bill lands on the ~3.6×-slower
DCN — or the ~100×-below-DCN WAN once the axis spans regions; the
hierarchical communicator's mixed split keeps the 2·k·(S−1)/S intra-slice
legs on ICI, ships (K/R−1)·k/S across DCN, and only (R−1) aggressively
re-coded shards across WAN. Collapsing the legs into one bandwidth erases
exactly the distinction the topology-aware selection exists to exploit
(ScaleCom's W-dependent topk degradation, EQuARX's per-topology tuning —
PAPERS.md).

Model limits (recorded in the evidence, enforced by the measured stage):

* **wire-dominated**: the static stage prices every candidate at the SAME
  base compute step — codec compute cost (topk selection, qsgd quantize,
  pallas fusion) is deliberately NOT modeled, because the repo's own
  bench history shows it is unpredictable from first principles (the
  staged qsgd path measured 42% slower than the kernel; chunk vs exact
  top-k is a 2× swing). That is what the measured shortlist is for.
* **no overlap** — with ONE declared exception: a double-buffered
  communicator (``pipeline=P`` on Ring/Hier, ISSUE 19) advertises its own
  ``wire_overlap_fraction()`` = ``WIRE_PIPELINE_EFFICIENCY · (P−1)/P``,
  and the wire leg is discounted by exactly that factor
  (``step = base + wire · (1 − overlap)``). The discount is honest
  because it is *statically refereed*: flow pass 5 requires the traced
  graph of a pipelined config to expose ≥ P independent
  compress→exchange chains before the config lints clean, so a
  communicator claiming the discount without the schedule to back it is
  a lint error, not an optimistic projection. Everything else keeps the
  NO-OVERLAP upper bound; the pass-5 static overlap bound still rides
  along per candidate as the honesty reference for the measured
  sandwich.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Any, Dict, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _bench_module():
    """The repo-root ``bench`` module (stdlib-only at import time). The
    tuner lives inside the package, so add the checkout root when running
    from an installed layout."""
    try:
        import bench
    except ImportError:
        sys.path.insert(0, ROOT)
        import bench
    if not hasattr(bench, "PROJECTION_MODEL"):
        raise ImportError(
            "a different top-level module shadows the repo's bench.py — "
            "the tuner needs bench.PROJECTION_MODEL's bandwidth constants")
    return bench


def projection_constants():
    """(ici_bytes_per_s, dcn_bytes_per_s, wan_bytes_per_s,
    projection_model_doc) — the ONE set of bandwidth assumptions, owned
    by bench.py."""
    bench = _bench_module()
    return (bench.ICI_RING_BYTES_PER_S, bench.DCN_BYTES_PER_S,
            bench.WAN_BYTES_PER_S, bench.PROJECTION_MODEL)


@dataclasses.dataclass(frozen=True)
class TuneTopology:
    """The tuner's target mesh: dp world size + ICI slice width + optional
    region width and fsdp width (the 2-D sharded-model mesh).

    ``slice_size=None`` is a single ICI slice of any width (the regime
    every committed single-chip measurement ran in); ``W=256, slice8`` is
    the xslice projection topology; a third spec part adds the WAN tier
    (``1024,8,256`` = 4 regions of 256 ranks, 32 slices of 8 each).
    Parsed from the CLI's ``W`` / ``W,slice_size[,region_size]`` /
    ``dp×fsdp[,slice_size[,region_size]]`` spelling (``64x4,8`` = dp=64 ×
    fsdp=4, slices of 8). ``world`` is the EXCHANGE (dp) axis size — the
    span every wire/numeric model prices, because the compressed
    collective is the per-shard reduce over dp; ``fsdp`` multiplies the
    device count without widening any priced collective.
    """

    world: int
    slice_size: Optional[int] = None
    fsdp: Optional[int] = None
    region_size: Optional[int] = None

    def __post_init__(self):
        if self.world < 1:
            raise ValueError(f"world must be >= 1; got {self.world}")
        if self.slice_size is not None and self.slice_size < 1:
            raise ValueError(
                f"slice_size must be >= 1 or None; got {self.slice_size}")
        if self.fsdp is not None and self.fsdp < 1:
            raise ValueError(f"fsdp must be >= 1 or None; got {self.fsdp}")
        if self.region_size is not None and self.slice_size is None:
            raise ValueError(
                "region_size requires slice_size — the WAN tier nests "
                "outside the slice tier")
        if self.region_size is not None:
            # mirror core.Topology's tier-nesting contract at parse time,
            # so an impossible spec dies on the CLI, not mid-funnel
            if (self.region_size < 1
                    or self.region_size % self.slice_size != 0):
                raise ValueError(
                    f"region_size {self.region_size} must be a whole "
                    f"multiple of slice_size {self.slice_size} — regions "
                    "are made of whole slices")

    @classmethod
    def parse(cls, text: str) -> "TuneTopology":
        parts = [p.strip() for p in str(text).split(",") if p.strip()]
        if not parts or len(parts) > 3:
            raise ValueError(
                f"topology spec {text!r} is not 'W', "
                "'W,slice_size[,region_size]', or "
                "'DPxFSDP[,slice_size[,region_size]]'")
        head = parts[0].lower().replace("×", "x")
        if "x" in head:
            dp_s, fsdp_s = head.split("x", 1)
            world, fsdp = int(dp_s), int(fsdp_s)
        else:
            world, fsdp = int(head), None
        slice_size = int(parts[1]) if len(parts) >= 2 else None
        region_size = int(parts[2]) if len(parts) == 3 else None
        return cls(world=world, slice_size=slice_size, fsdp=fsdp,
                   region_size=region_size)

    def core_topology(self):
        from grace_tpu.core import Topology
        return Topology(slice_size=self.slice_size,
                        region_size=self.region_size)

    @property
    def devices(self) -> int:
        """Total device count: dp × fsdp."""
        return self.world * (self.fsdp or 1)

    @property
    def label(self) -> str:
        w = (f"W{self.world}" if self.fsdp is None
             else f"W{self.world}x{self.fsdp}")
        if self.slice_size is None:
            return w
        if self.region_size is None:
            return f"{w}/slice{self.slice_size}"
        return f"{w}/slice{self.slice_size}/region{self.region_size}"


def dense_bytes(model_structs) -> int:
    """Dense gradient bytes of a param pytree (structs or arrays)."""
    import jax
    import numpy as np

    return int(sum(
        int(np.prod(l.shape, dtype=np.int64)) * np.dtype(l.dtype).itemsize
        for l in jax.tree_util.tree_leaves(model_structs)))


def n_elements(model_structs) -> int:
    import jax
    import numpy as np

    return int(sum(int(np.prod(l.shape, dtype=np.int64))
                   for l in jax.tree_util.tree_leaves(model_structs)))


def price_candidate(grace, model_structs, spec: TuneTopology, *,
                    base_step_s: float = 0.0,
                    dense_step_s: Optional[float] = None) -> Dict[str, Any]:
    """One candidate's static price under the target topology.

    ``base_step_s`` is the compute-side step time assumed for EVERY
    candidate (0.0 = pure wire ranking; the measured stage replaces it
    with each candidate's own timed step); ``dense_step_s`` defaults to
    the same value so the speedup ratio stays like-for-like. Dense rides
    a ring allreduce priced through the identical shared model
    (``bench.project_multichip``'s convention).
    """
    from grace_tpu.comm import Allreduce
    from grace_tpu.utils import wire_report

    ici_bw, dcn_bw, wan_bw, _ = projection_constants()
    dense_step_s = base_step_s if dense_step_s is None else dense_step_s
    rep = wire_report(grace.compressor, model_structs)
    n = n_elements(model_structs)
    dense_b = dense_bytes(model_structs)
    vote = bool(getattr(grace.compressor, "vote_aggregate", False))
    topo = spec.core_topology()
    link = grace.communicator.recv_link_bytes(
        rep.wire_bytes, n, spec.world, topology=topo, vote=vote)
    # Shared-scale negotiation collectives, priced honestly into the wire
    # bill (Compressor.negotiation_nbytes × one negotiate per compress
    # call of the fusion plan; 0 for every other codec). The pmax is a
    # flat full-axis collective, so — like the watch gather — it rides ICI
    # within one slice and DCN the moment the axis crosses slices.
    import jax

    from grace_tpu.transform import fusion_payload_structs

    n_calls = sum(count for _, count in fusion_payload_structs(
        jax.tree_util.tree_leaves(model_structs), grace.fusion))
    neg_b = n_calls * int(grace.compressor.negotiation_nbytes(spec.world))
    if neg_b:
        # Flat full-axis collective: priced on the worst tier the axis
        # spans — the same flat_tier rule the telemetry fold uses.
        tier = topo.flat_tier(spec.world)
        link = link._replace(**{tier: getattr(link, tier) + neg_b})
    dense_link = Allreduce(
        axis_name=grace.communicator.axis_name).recv_link_bytes(
            dense_b, n, spec.world, topology=topo)

    def wire_s(lb):
        return lb.ici / ici_bw + lb.dcn / dcn_bw + lb.wan / wan_bw

    # wire_pipeline discount: the communicator's OWN declared overlap
    # fraction (0.0 everywhere except the double-buffered ring/hier
    # schedules, whose claim flow pass 5 referees statically — see the
    # module docstring's model-limits note). Dense always rides the flat
    # undiscounted psum bracket.
    overlap = float(getattr(grace.communicator, "wire_overlap_fraction",
                            lambda: 0.0)())
    step_s = base_step_s + wire_s(link) * (1.0 - overlap)
    d_step_s = dense_step_s + wire_s(dense_link)
    adapt = getattr(grace, "adapt", None)
    extra: Dict[str, Any] = {}
    if adapt is not None:
        # graft-adapt candidates are priced at their STEADY STATE — the
        # top rung IS the base compressor (normalize_adapt's contract),
        # so the headline projected_step_ms above is exactly the static
        # top-rung config's: a quiet adaptive run matches the
        # hand-picked winner's projected throughput by construction.
        # The full rung schedule rides along so the funnel record shows
        # what each degradation level costs — the transparency the
        # "price adaptive candidates by their rung schedule" contract
        # asks for.
        extra = {
            "steady_state_rung": len(adapt.ladder),
            "rung_prices": adapt_rung_prices(grace, model_structs, spec,
                                             base_step_s=base_step_s),
        }
    return {
        **extra,
        "payload_bytes": int(rep.wire_bytes),
        "wire_ratio": round(rep.wire_bytes / max(1, dense_b), 6),
        "negotiation_bytes": int(neg_b),
        "ici_bytes": int(link.ici),
        "dcn_bytes": int(link.dcn),
        "wan_bytes": int(link.wan),
        "wire_ms": round(wire_s(link) * 1e3, 9),
        "wire_pipeline_overlap": round(overlap, 6),
        "dense_ici_bytes": int(dense_link.ici),
        "dense_dcn_bytes": int(dense_link.dcn),
        "dense_wan_bytes": int(dense_link.wan),
        "dense_wire_ms": round(wire_s(dense_link) * 1e3, 9),
        "projected_step_ms": round(step_s * 1e3, 9),
        "dense_projected_step_ms": round(d_step_s * 1e3, 9),
        "predicted_speedup_vs_dense": round(d_step_s / step_s, 4)
        if step_s > 0 else None,
    }


def adapt_rung_prices(grace, model_structs, spec: TuneTopology, *,
                      base_step_s: float = 0.0):
    """Static per-rung prices of a graft-adapt candidate's whole
    degradation ladder: rung 0 is the dense escape psum (the same
    Allreduce pricing the dense bracket uses, at the escape codec's
    payload width), rung r >= 1 the ladder codec through the candidate's
    own communicator — each through the identical shared per-link model,
    so the controller's state-dependent wire bill is an enumerated fact
    in the funnel record, not a surprise at run time."""
    from grace_tpu.comm import Allreduce
    from grace_tpu.utils import wire_report

    ici_bw, dcn_bw, wan_bw, _ = projection_constants()
    n = n_elements(model_structs)
    topo = spec.core_topology()

    def wire_s(lb):
        return lb.ici / ici_bw + lb.dcn / dcn_bw + lb.wan / wan_bw

    out = []
    esc = getattr(grace, "escape", None)
    esc_b = (wire_report(esc, model_structs).wire_bytes
             if esc is not None else dense_bytes(model_structs))
    link0 = Allreduce(
        axis_name=grace.communicator.axis_name).recv_link_bytes(
            esc_b, n, spec.world, topology=topo)
    out.append({"rung": 0,
                "codec": (type(esc).__name__ if esc is not None
                          else "dense"),
                "payload_bytes": int(esc_b),
                "ici_bytes": int(link0.ici), "dcn_bytes": int(link0.dcn),
                "wan_bytes": int(link0.wan),
                "projected_step_ms": round(
                    (base_step_s + wire_s(link0)) * 1e3, 9)})
    for ri, comp in enumerate(grace.adapt.ladder, start=1):
        rep = wire_report(comp, model_structs)
        vote = bool(getattr(comp, "vote_aggregate", False))
        link = grace.communicator.recv_link_bytes(
            rep.wire_bytes, n, spec.world, topology=topo, vote=vote)
        neg = int(comp.negotiation_nbytes(spec.world))
        out.append({"rung": ri, "codec": type(comp).__name__,
                    "payload_bytes": int(rep.wire_bytes),
                    "negotiation_bytes": neg,
                    "ici_bytes": int(link.ici),
                    "dcn_bytes": int(link.dcn),
                    "wan_bytes": int(link.wan),
                    "projected_step_ms": round(
                        (base_step_s + wire_s(link)) * 1e3, 9)})
    return out
