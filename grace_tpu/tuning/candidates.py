"""Candidate enumeration: the audited registry plus tuner-generated variants.

The tuner does not invent configs from thin air — its search space is the
same (codec, communicator, fusion, pallas, precision) matrix the rest of
the repo already enforces:

* **registry candidates** come verbatim from the static auditor's
  ``AUDIT_CONFIGS`` (update-mode entries only; resilience/observability variants are
  orthogonal to the selection problem and the escape cond makes "the"
  wire cost bimodal, so escape/telemetry/watch/consensus entries are
  skipped, as is the no-exchange ``identity`` entry — a zero-byte price
  would win every ranking while exchanging nothing);
* **generated variants** cross the measured winning families with the
  knobs a topology turn makes relevant — the hierarchical communicator at
  the target slice width, the bucketed overlap executor's ``fusion=1024``,
  the packed qsgd4 wire format, and its Pallas fused-kernel twin
  (``tpu_only``: interpret mode off-chip is a per-element emulation).

Legality is decided by the SAME capability gates the communicators raise
at build/trace time (``summable_payload`` / ``supports_hop_requant`` /
statelessness / vote routing / world-divisibility) — re-stated here as a
cheap static predicate so an illegal combo is recorded in the prune
funnel with the communicator's own rationale instead of surfacing as a
mid-measurement ``TypeError``. ``tests/test_tuning.py`` pins that every
gate here agrees with the runtime one it mirrors.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from grace_tpu.tuning.cost import TuneTopology

__all__ = ["Candidate", "enumerate_candidates", "candidate_legal",
           "variant_audit_entries"]


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One (codec, communicator, fusion, pallas, precision) combination."""

    name: str
    params: Dict[str, Any]
    source: str = "registry"        # "registry" | "generated"
    tpu_only: bool = False          # skip in off-chip measurement

    def build(self):
        from grace_tpu.helper import grace_from_params
        return grace_from_params(dict(self.params))


# Params keys that select resilience/observability machinery rather than
# the exchange itself — entries carrying them are not selection candidates.
_NON_SELECTION_KEYS = ("escape", "telemetry", "watch", "consensus")


def registry_candidates() -> List[Candidate]:
    from grace_tpu.analysis.configs import AUDIT_CONFIGS

    out = []
    for e in AUDIT_CONFIGS:
        if e.get("mode", "update") != "update":
            continue
        p = dict(e["params"])
        if any(k in p for k in _NON_SELECTION_KEYS):
            continue
        if p.get("communicator") in ("identity", "none"):
            continue
        out.append(Candidate(name=e["name"], params=p, source="registry",
                             tpu_only=bool(p.get("use_pallas") is True)))
    return out


def generated_variants(spec: TuneTopology) -> List[Candidate]:
    """Deterministic topology-aware variants beyond the registry.

    Only generated for knobs the registry leaves uncovered at this target:
    hier at the *target* slice width (the registry pins slice_size=4 for
    the world-8 audit mesh), the bucketed executor over the small-mesh
    winners, and the packed-qsgd4 Pallas twin for the chip window.
    """
    topk = {"compressor": "topk", "compress_ratio": 0.01,
            "topk_algorithm": "chunk", "memory": "residual"}
    qsgd4 = {"compressor": "qsgd", "quantum_num": 7, "use_pallas": False,
             "memory": "none"}
    # Aggregation-homomorphic qsgd4 (payload_algebra='shared_scale'):
    # requant chain 0 at ANY world, so unlike the flat qsgd ring it
    # survives the degradation gate at pod scale — the funnel can finally
    # rank a flat-ring codec at W=256 without the ScaleCom cliff.
    homoq = {"compressor": "homoqsgd", "quantum_num": 7,
             "memory": "residual"}
    out = [
        Candidate("tune-topk1pct-allgather-bucketed",
                  {**topk, "communicator": "allgather", "fusion": 1024},
                  source="generated"),
        Candidate("tune-topk1pct-ring-bucketed",
                  {**topk, "communicator": "ring", "fusion": 1024},
                  source="generated"),
        Candidate("tune-qsgd4-ring-packed-bucketed",
                  {**qsgd4, "communicator": "ring", "fusion": 1024},
                  source="generated"),
        Candidate("tune-qsgd4-ring-packed-bucketed-pallas",
                  {**qsgd4, "use_pallas": True, "communicator": "ring",
                   "fusion": 1024},
                  source="generated", tpu_only=True),
        Candidate("tune-homoqsgd4-ring",
                  {**homoq, "communicator": "ring", "fusion": "flat"},
                  source="generated"),
        # Double-buffered ring schedule (ISSUE 19): pipeline=2 splits
        # the fused flat buffer into two segments whose full ring
        # schedules overlap on real links — priced with the
        # wire_pipeline discount (cost.price_candidate reads
        # comm.wire_overlap_fraction()), statically refereed by flow
        # pass 5's >= P independent-chain requirement. (The 2-bit
        # sibling needs no generated variant: the registered
        # qsgd2-ring-packed-pipelined entry is already a registry
        # candidate.)
        Candidate("tune-qsgd4-ring-packed-pipelined",
                  {**qsgd4, "communicator": "ring", "fusion": "flat",
                   "pipeline": 2},
                  source="generated"),
        # Self-tuning adaptive candidate (ISSUE 15): the graft-adapt
        # degradation ladder (dense escape → homoqsgd8 → homoqsgd4) over
        # the zero-requant ring. Priced at its STEADY STATE (the top
        # rung == the static homoqsgd4 ring, so a quiet run matches the
        # static winner's projected throughput exactly); the funnel's
        # numeric gate additionally checks EVERY rung's
        # payload_sum_max_world at the target world, and the per-rung
        # prices ride the funnel record as rung_prices. Same ladder as
        # the lint-registered adapt-homoqsgd-ring entry, so everything
        # the tuner can shortlist here is a statically audited schedule.
        Candidate("tune-adapt-homoqsgd4-ring",
                  {**homoq, "communicator": "ring", "fusion": "flat",
                   "escape": "fp16", "telemetry": 16,
                   "adapt": {"window": 25,
                             "ladder": [{"quantum_num": 127}]}},
                  source="generated"),
        # The FSDP exchange (ISSUE 14): one all_to_all + one all_gather,
        # requant chain ≤ 1 at ANY world — the flat-topology schedule
        # that survives the degradation gate where the hop-requant ring
        # dies at pod scale.
        Candidate("tune-topk1pct-rscatter",
                  {**topk, "communicator": "rscatter", "fusion": "flat"},
                  source="generated"),
        Candidate("tune-homoqsgd4-rscatter",
                  {**homoq, "communicator": "rscatter", "fusion": "flat"},
                  source="generated"),
    ]
    if spec.fsdp is not None and spec.fsdp > 1:
        # Sharded-model target: the routed transformer-track shape — the
        # bulk of the gradient rides sparsification through the per-shard
        # reduce-scatter, LayerNorm/bias leaves ride dense fp16 psum.
        out.append(Candidate(
            "tune-routed-rscatter-fsdp",
            {**topk, "communicator": "rscatter", "fsdp_axis": "fsdp",
             "route": [("*ln*", {"compressor": "fp16", "memory": "none",
                                 "communicator": "allreduce"}),
                       ("*bias*", {"compressor": "fp16", "memory": "none",
                                   "communicator": "allreduce"})]},
            source="generated"))
    s = spec.slice_size
    if s is not None and spec.world > s:
        out += [
            Candidate(f"tune-topk1pct-hier{s}",
                      {**topk, "communicator": "hier", "slice_size": s,
                       "fusion": "flat"}, source="generated"),
            Candidate(f"tune-topk1pct-hier{s}-bucketed",
                      {**topk, "communicator": "hier", "slice_size": s,
                       "fusion": 1024}, source="generated"),
            Candidate(f"tune-qsgd4-hier{s}-packed",
                      {**qsgd4, "communicator": "hier", "slice_size": s,
                       "fusion": "flat"}, source="generated"),
            Candidate(f"tune-homoqsgd4-hier{s}",
                      {**homoq, "communicator": "hier", "slice_size": s,
                       "fusion": "flat"}, source="generated"),
        ]
    rz = spec.region_size
    if s is not None and rz is not None and spec.world > rz:
        # Three-tier target (ISSUE 16): the three-level schedule at the
        # target's own (slice, region) widths. The topk variant arms the
        # aggressive per-level WAN codec (a deeper-ratio topk re-encode of
        # the region partial — ONE boundary requant); the homomorphic one
        # crosses WAN exactly-summable and must not (gate-enforced).
        out += [
            Candidate(f"tune-topk1pct-hier{s}r{rz}",
                      {**topk, "communicator": "hier", "slice_size": s,
                       "region_size": rz, "fusion": "flat",
                       "wan_compressor": {"compressor": "topk",
                                          "compress_ratio": 0.001,
                                          "topk_algorithm": "chunk"}},
                      source="generated"),
            Candidate(f"tune-homoqsgd4-hier{s}r{rz}",
                      {**homoq, "communicator": "hier", "slice_size": s,
                       "region_size": rz, "fusion": "flat"},
                      source="generated"),
        ]
    return out


def enumerate_candidates(spec: TuneTopology) -> List[Candidate]:
    """Registry + generated, deduped by name (registry wins — a generated
    variant colliding with a registered entry IS that entry)."""
    cands = registry_candidates()
    seen = {c.name for c in cands}
    for c in generated_variants(spec):
        if c.name not in seen:
            cands.append(c)
            seen.add(c.name)
    return cands


def _compressor_stateful(compressor) -> bool:
    """Whether the codec carries cross-step per-leaf state (Signum
    momentum, PowerSGD Q) — the shard-parallel communicators reject those
    at step time because chunked shards give the state no meaning."""
    import jax
    import jax.numpy as jnp

    try:
        s = jax.eval_shape(compressor.init_state,
                           jax.ShapeDtypeStruct((8,), jnp.float32))
    except Exception:       # in-compress collectives etc.: assume stateful
        return True
    return s is not None


def _triad_legal(comp, cm, spec: TuneTopology) -> Optional[str]:
    """The static mirror of the communicators' build/step-time gates for
    one (compressor, communicator) pair at the TARGET world — the reason
    the runtime would raise, or None."""
    from grace_tpu import comm

    w = spec.world
    vote = bool(getattr(comp, "vote_aggregate", False))
    summable = bool(getattr(comp, "summable_payload", False))
    requant = bool(getattr(comp, "supports_hop_requant", False))
    shard_parallel = (comm.TwoShotAllreduce, comm.RingAllreduce,
                      comm.ReduceScatterAllreduce,
                      comm.HierarchicalAllreduce)

    if isinstance(cm, comm.SignAllreduce) and not vote:
        return ("SignAllreduce requires vote_aggregate=True "
                f"({type(comp).__name__} declares False) — the "
                "re-sign would drop its aggregate's scaling")
    if type(cm) is comm.Allreduce and not (vote or summable):
        return ("Allreduce requires summable_payload=True "
                f"({type(comp).__name__} declares False) — per-rank "
                "payloads decode differently")
    if isinstance(cm, shard_parallel):
        if _compressor_stateful(comp):
            return (f"{type(cm).__name__} requires a stateless "
                    f"compressor; {type(comp).__name__} carries "
                    "cross-step state with no per-chunk meaning")
        # The data-free-ctx soundness condition _shard_compress raises at
        # step time (ranks decode each other's shard payloads with
        # locally derived ctx) — mirrored here so a codec whose whole-
        # buffer negotiation cannot be sharded (cyclic Top-K's index set)
        # dies in the funnel with the runtime's own rationale instead of
        # a mid-measurement TypeError. shared_scale codecs are exempt:
        # their hoisted negotiation replaces the gate.
        if getattr(comp, "payload_algebra", None) != "shared_scale":
            import jax.numpy as jnp

            from grace_tpu.comm import ctx_is_data_free
            try:
                data_free = ctx_is_data_free(comp, 64, jnp.float32)
            except Exception:
                data_free = False
            if not data_free:
                return (f"{type(cm).__name__} requires a data-free ctx; "
                        f"{type(comp).__name__}.compress puts "
                        "data-derived arrays in ctx — other ranks' shards "
                        "would decode against the wrong values")
    if isinstance(cm, (comm.RingAllreduce, comm.ReduceScatterAllreduce,
                       comm.HierarchicalAllreduce)) \
            and not (summable or requant):
        return (f"{type(cm).__name__} keeps the payload compressed "
                "on every hop, which needs a payload algebra "
                "(exact/shared_scale/sketch — summable_payload) or "
                f"supports_hop_requant; {type(comp).__name__} "
                "declares neither")
    if isinstance(cm, comm.HierarchicalAllreduce):
        s = cm.slice_size
        if s is not None and w > s and w % s:
            return (f"HierarchicalAllreduce(slice_size={s}) does "
                    f"not divide world {w} — the two-level schedule "
                    "needs whole slices")
    return None


def candidate_legal(candidate: Candidate, spec: TuneTopology
                    ) -> Tuple[bool, Optional[str], Any]:
    """(legal, reason, grace) — the static mirror of the communicators'
    build/step-time gates, evaluated at the TARGET world. ``grace`` is the
    built bundle when construction succeeded (legal or not), else None.
    Routed candidates check every route's sub-triad too (plus the
    routes×fusion build gate grace_transform enforces), so an illegal
    routed combo dies in the funnel with the runtime's own rationale."""
    try:
        grace = candidate.build()
    except (TypeError, ValueError) as e:
        return False, f"does not build: {type(e).__name__}: {e}", None
    if getattr(grace, "routes", None) and grace.fusion is not None:
        return False, ("routes=... requires fusion=None: per-leaf codec "
                       "routing is per-leaf semantics (grace_transform "
                       "raises the same gate at build time)"), grace
    reason = _triad_legal(grace.compressor, grace.communicator, spec)
    if reason:
        return False, reason, grace
    for pat, comp, _mem, cm in (getattr(grace, "routes", None) or ()):
        reason = _triad_legal(comp, cm, spec)
        if reason:
            return False, f"route {pat!r}: {reason}", grace
    # graft-adapt ladders: every reachable rung must itself be a legal
    # triad with the candidate's communicator — the controller can
    # dispatch any rung mid-run, so one illegal rung is a runtime
    # TypeError waiting for the first tighten, mirrored here with the
    # communicator's own rationale.
    adapt = getattr(grace, "adapt", None)
    for ri, comp in enumerate(getattr(adapt, "ladder", ()) or ()):
        reason = _triad_legal(comp, grace.communicator, spec)
        if reason:
            return False, f"adapt rung {ri + 1}: {reason}", grace
    return True, None, grace


def variant_audit_entries() -> List[Tuple[str, Dict[str, Any], str]]:
    """The tuner-generated variants pinned into the static auditor's
    registry (``analysis.configs.AUDIT_CONFIGS`` appends these), so
    ``graft_lint --all-configs`` covers what the tuner can emit:
    (name, params, comment) triples. slice_size=4 puts a real boundary
    inside the 8-way audit mesh, same as the registered hier family.

    New coverage, not duplicates: the bucketed executor OVER the two-level
    hierarchical schedule (per-bucket intra-slice rings + grouped
    cross-slice gathers in one trace), and the 4-bit packed wire format
    requantized at hier's hop AND slice-boundary re-encode points.
    """
    topk = {"compressor": "topk", "compress_ratio": 0.01,
            "topk_algorithm": "chunk", "memory": "residual",
            "communicator": "hier", "slice_size": 4}
    return [
        ("tune-topk1pct-hier-bucketed", {**topk, "fusion": 1024},
         "bucketed executor x two-level hier schedule"),
        ("tune-qsgd4-hier-packed",
         {"compressor": "qsgd", "quantum_num": 7, "use_pallas": False,
          "memory": "none", "communicator": "hier", "slice_size": 4,
          "fusion": "flat"},
         "packed 4-bit wire over hier hop+boundary requant"),
        # The double-buffered ring the tuner can now emit (ISSUE 19): the
        # pipelined twin of the packed qsgd4 ring. Flow pass 5 must count
        # >= 2 independent chains off the grace/pipeline scope tags — the
        # static referee behind the wire_pipeline pricing discount. (The
        # 2-bit sibling is the separately registered
        # qsgd2-ring-packed-pipelined entry.)
        ("tune-qsgd4-ring-packed-pipelined",
         {"compressor": "qsgd", "quantum_num": 7, "use_pallas": False,
          "memory": "none", "communicator": "ring", "fusion": "flat",
          "pipeline": 2},
         "double-buffered packed ring; pass-5 pipelined-chain referee"),
        # The tuner's FSDP variants (ISSUE 14): the homomorphic rscatter
        # (zero requant through all_to_all + payload-space sum) must be a
        # lint-audited schedule, not just a funnel line.
        ("tune-homoqsgd4-rscatter",
         {"compressor": "homoqsgd", "quantum_num": 7, "memory": "residual",
          "communicator": "rscatter", "fusion": "flat"},
         "homomorphic payload-space sum over the rscatter schedule"),
        # The three-tier funnel's WAN-recompression leg (ISSUE 16): the
        # aggressive per-level codec that re-selects the slice-boundary
        # payload before it crosses the region boundary. slice_size=2 +
        # region_size=4 puts both boundaries inside the 8-way audit mesh,
        # so wire_reconciliation prices the narrowed WAN leg against
        # recv_link_bytes' p_wan while ici/dcn stay at the base width.
        ("tune-topk1pct-hier3-wan",
         {"compressor": "topk", "compress_ratio": 0.25,
          "topk_algorithm": "chunk", "memory": "residual",
          "communicator": "hier", "slice_size": 2, "region_size": 4,
          "fusion": "flat",
          "wan_compressor": {"compressor": "topk", "compress_ratio": 0.05,
                             "topk_algorithm": "chunk"}},
         "aggressive WAN re-compression over the three-level hier schedule"),
    ]
