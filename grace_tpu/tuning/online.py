"""Online re-tuning funnel: the offline tuner's decision loop, bounded
and re-runnable against a LIVE fleet mid-run.

``run_tune`` is an offline ceremony — it assumes it owns the process, may
trace/profile at leisure, and stamps its winner into ``TUNE_LAST.json``
for a human to adopt. The re-tuner cannot afford any of that: it runs
while a training job is paused at a drain boundary, its time budget is
the probation the fleet grants it, and a hung candidate measurement must
cost a bounded number of seconds, not the run. :func:`online_funnel` is
therefore run_tune's funnel with the offline parts cut away and the
bounded parts forced on:

* same **static funnel** (:func:`~grace_tpu.tuning.prune.static_prune`):
  capability gates, numeric safety at the live world, per-link wire
  pricing, flow passes — every rejection recorded with its reason, so a
  promotion's PREPARE audit can show why the winner beat the field;
* same **measured shortlist**
  (:func:`~grace_tpu.tuning.measure.measure_shortlist`) on the live mesh,
  but with ``measure_timeout_s`` REQUIRED in spirit: the default here is
  a finite timeout, and a hung candidate lands in ``skipped`` with
  ``verdict='measure_timeout'`` after bounded retries with doubling
  backoff instead of stalling the controller;
* **no overlap sandwich, no evidence stamp** — the honesty gate for an
  online promotion is the transaction itself
  (:class:`~grace_tpu.resilience.retune.RetuneController`: lint audit,
  footprint check, consensus-gated cutover, probation with automatic
  demotion), which supersedes the offline sandwich's role;
* an ``include`` hook so the controller can force the incumbent and any
  operator-prescribed candidates (a PowerSGD rank ladder, a dense escape)
  into the field even when enumeration would not generate them.

The returned document is the PREPARE record's ``funnel`` payload: static
funnel, measured rows, skip verdicts, winner name + loadable
``winner_params``.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Sequence, Union

from grace_tpu.tuning.candidates import Candidate, enumerate_candidates
from grace_tpu.tuning.cost import TuneTopology
from grace_tpu.tuning.measure import measure_shortlist, model_structs
from grace_tpu.tuning.prune import static_prune

__all__ = ["ONLINE_MEASURE_TIMEOUT_S", "online_funnel"]

# The online default is FINITE: a re-tune decision taken mid-run must
# never inherit the offline tuner's wait-forever behavior. Callers can
# widen it (or pass None to opt back into unbounded, e.g. under a
# debugger) but they have to do it on purpose.
ONLINE_MEASURE_TIMEOUT_S = 120.0


def online_funnel(topology: Union[str, TuneTopology], mesh, *,
                  model: str = "toy", shortlist_n: int = 3,
                  audit_world: int = 8, timed_steps: int = 4,
                  repeats: int = 1, seed: int = 0,
                  measure_timeout_s: Optional[float]
                  = ONLINE_MEASURE_TIMEOUT_S,
                  measure_retries: int = 1,
                  include: Optional[Sequence[Candidate]] = None,
                  exclude: Iterable[str] = ()) -> Dict[str, Any]:
    """One bounded re-tune decision against the live mesh.

    Enumerates candidates for ``topology`` (plus any ``include``d ones,
    minus ``exclude``d names), runs the static funnel, measures the
    shortlist with bounded per-candidate timeouts, and returns::

        {"topology", "static", "measured", "winner", "winner_params"}

    ``winner`` is None when nothing survived to a measurement — the
    controller treats that as "stay on the incumbent", never as an error.
    """
    spec = (topology if isinstance(topology, TuneTopology)
            else TuneTopology.parse(topology))
    structs = model_structs(model)
    cands = list(enumerate_candidates(spec))
    if include:
        names = {c.name for c in cands}
        cands += [c for c in include if c.name not in names]
    drop = set(exclude)
    if drop:
        cands = [c for c in cands if c.name not in drop]
    funnel = static_prune(cands, spec, structs, audit_world=audit_world,
                          shortlist_n=shortlist_n)
    by_name = {c.name: c for c in cands}
    shortlist = [by_name[n] for n in funnel["shortlist"]]
    measured = measure_shortlist(
        shortlist, spec, mesh, model=model, timed_steps=timed_steps,
        repeats=repeats, seed=seed, measure_timeout_s=measure_timeout_s,
        measure_retries=measure_retries)
    winner = measured["winner"]
    return {
        "topology": spec.label,
        "static": funnel,
        "measured": measured,
        "winner": winner,
        "winner_params": (dict(by_name[winner].params)
                         if winner is not None else None),
    }
