"""graft-tune: the topology-aware autotuner (ROADMAP item 1).

The first subsystem that consumes the repo's seven lint passes and the
shared per-link wire model as *inputs to a decision* rather than as gates:
given a model's param tree and a target mesh topology, it

1. **enumerates** (codec, communicator, fusion, pallas, precision)
   candidates from the static auditor's registry plus topology-aware
   generated variants (:mod:`.candidates`), rejecting illegal combos with
   the same capability gates the communicators enforce;
2. **prunes statically** (:mod:`.prune`): numeric safety at the target
   world, per-link wire pricing under the target
   :class:`~grace_tpu.core.Topology` through the documented
   wire-dominated cost model (:mod:`.cost`), flow pass 5/6/7 over the
   ranked survivors — every rejection recorded with its reason;
3. **measures the shortlist** (:mod:`.measure`): real timed steps with
   bench.py's timing discipline, dense brackets interleaved same-session,
   each candidate's own measured compute step substituted back into the
   cost model for the target-topology ranking;
4. **stamps the winner**: a ``grace_from_params``-loadable config with git
   revision, topology, the prune funnel, and the measured≤static overlap
   sandwich as its honesty gate, written to ``TUNE_LAST.json``
   (rendered by ``tools/evidence_summary.py``).

CLI: ``tools/graft_tune.py``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Union

from grace_tpu.tuning.candidates import (Candidate, candidate_legal,
                                         enumerate_candidates,
                                         variant_audit_entries)
from grace_tpu.tuning.cost import TuneTopology, price_candidate, \
    projection_constants
from grace_tpu.tuning.measure import (MeasureTimeout, bounded_call,
                                      build_model_step, measure_shortlist,
                                      model_structs, overlap_sandwich)
from grace_tpu.tuning.online import ONLINE_MEASURE_TIMEOUT_S, online_funnel
from grace_tpu.tuning.prune import numeric_verdict, static_prune

__all__ = ["Candidate", "MeasureTimeout", "ONLINE_MEASURE_TIMEOUT_S",
           "TuneTopology", "bounded_call",
           "candidate_legal", "online_funnel",
           "enumerate_candidates", "measure_shortlist", "model_structs",
           "numeric_verdict", "overlap_sandwich", "price_candidate",
           "projection_constants", "run_tune", "static_prune",
           "variant_audit_entries", "write_tune_evidence",
           "TUNE_EVIDENCE_PATH"]

TUNE_EVIDENCE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "TUNE_LAST.json")


def run_tune(topologies: Sequence[Union[str, TuneTopology]], *,
             model: str = "toy", shortlist_n: int = 3,
             static_only: bool = False, audit_world: int = 8,
             timed_steps: int = 8, repeats: int = 2, seed: int = 0,
             measure_timeout_s: Optional[float] = None,
             measure_retries: int = 2,
             mesh=None, trace_dir: Optional[str] = None,
             argv: str = "") -> Dict[str, Any]:
    """The whole tuning loop; returns the ``TUNE_LAST.json`` document.

    The FIRST topology is the decision target (its shortlist is measured
    and its winner stamped); the rest get static rankings only — the
    ``--static-only`` registry survey ranks every listed topology. The
    document's ``ok`` field is the CLI's exit-0 condition: static runs are
    ok by construction, measured runs require a winner whose overlap
    sandwich holds.
    """
    specs = [t if isinstance(t, TuneTopology) else TuneTopology.parse(t)
             for t in topologies]
    if not specs:
        raise ValueError("at least one topology is required")
    target = specs[0]
    structs = model_structs(model)
    ici_bw, dcn_bw, wan_bw, projection_model = projection_constants()

    static: Dict[str, Any] = {}
    candidates_by_name: Dict[str, Candidate] = {}
    for spec in specs:
        cands = enumerate_candidates(spec)
        for c in cands:
            candidates_by_name.setdefault(c.name, c)
        static[spec.label] = static_prune(
            cands, spec, structs, audit_world=audit_world,
            shortlist_n=shortlist_n)

    doc: Dict[str, Any] = {
        "tool": "graft_tune",
        "model": model,
        "topologies": [{"world": s.world, "slice_size": s.slice_size,
                        "region_size": s.region_size,
                        "label": s.label} for s in specs],
        "target": target.label,
        "cost_model": {
            "ici_bytes_per_s": ici_bw,
            "dcn_bytes_per_s": dcn_bw,
            "wan_bytes_per_s": wan_bw,
            "rule": "projected_step = base_compute_step + ici_bytes/ICI_BW"
                    " + dcn_bytes/DCN_BW + wan_bytes/WAN_BW (per-link "
                    "recv_link_bytes under the target Topology; see "
                    "grace_tpu/tuning/cost.py)",
            "constants_source": projection_model["constants_source"],
        },
        "static": static,
        "static_only": bool(static_only),
        "ok": True,
    }

    if not static_only:
        target_prune = static[target.label]
        shortlist = [candidates_by_name[n]
                     for n in target_prune["shortlist"]]
        if mesh is None:
            import jax

            from grace_tpu.parallel import data_parallel_mesh
            mesh = data_parallel_mesh(jax.devices())
        measured = measure_shortlist(
            shortlist, target, mesh, model=model,
            timed_steps=timed_steps, repeats=repeats, seed=seed,
            measure_timeout_s=measure_timeout_s,
            measure_retries=measure_retries)
        doc["measured"] = measured
        winner_name = measured["winner"]
        if winner_name is None:
            doc["ok"] = False
            doc["error"] = "no shortlisted candidate produced a measurement"
        else:
            if trace_dir is None:
                import tempfile
                trace_dir = tempfile.mkdtemp(prefix="graft_tune_prof_")
            sandwich = overlap_sandwich(
                candidates_by_name[winner_name], mesh, trace_dir,
                model=model, seed=seed)
            funnel_rec = next(
                r for r in target_prune["funnel"]
                if r["candidate"] == winner_name)
            row = next(r for r in measured["rows"]
                       if r["candidate"] == winner_name)
            doc["winner"] = {
                "candidate": winner_name,
                # The loadable config: grace_from_params(winner["grace_params"])
                # rebuilds the winning triad verbatim.
                "grace_params": dict(
                    candidates_by_name[winner_name].params),
                "topology": {"world": target.world,
                             "slice_size": target.slice_size},
                "predicted": funnel_rec.get("predicted"),
                "static_overlap_bound":
                    (funnel_rec.get("flow") or {}).get("overlap_bound"),
                "measured": row,
                "overlap_sandwich": sandwich,
            }
            doc["ok"] = bool(sandwich["holds"])

    # Provenance last: everything above is deterministic for a fixed
    # registry + topology (the determinism contract tests/test_tuning.py
    # pins, modulo these stamps).
    try:
        from grace_tpu.utils.logging import run_provenance
        doc["provenance"] = run_provenance(
            data="synthetic", tool="graft_tune", argv=argv)
    except Exception:                                    # noqa: BLE001
        doc["provenance"] = None
    import datetime
    doc["captured_at"] = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    return doc


def write_tune_evidence(doc: Dict[str, Any],
                        path: str = TUNE_EVIDENCE_PATH) -> None:
    """Atomic tmp+fsync+replace, the repo's evidence-write idiom, plus a
    ledger record (repo-root artifacts only — a test writing to tmp_path
    must not touch EVIDENCE/ledger.jsonl)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    if (os.path.dirname(os.path.abspath(path)) !=
            os.path.dirname(os.path.abspath(TUNE_EVIDENCE_PATH))):
        return
    try:
        from grace_tpu.evidence.ledger import record_artifact
        prov = doc.get("provenance") or {}
        winner = doc.get("winner") or {}
        n_dev = prov.get("n_devices")
        record_artifact(
            path, id="tune-winner", metric="tune_winner_config",
            value=winner.get("candidate"), claim_class="measured",
            tool="graft_tune", platform=prov.get("platform"),
            chip=prov.get("device"), n_devices=n_dev,
            topology={"world": n_dev, "tiers": ["ici"], "slice": None,
                      "region": None},
            config=winner.get("grace_params"),
            lint_clean=bool(doc.get("ok")))
    except Exception as e:                               # noqa: BLE001
        import sys
        print(f"[graft_tune] ledger emission failed: {e}",
              file=sys.stderr, flush=True)
