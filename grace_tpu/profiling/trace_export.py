"""Chrome-trace *export*: the write side graft-prof never had.

:mod:`~grace_tpu.profiling.trace_analysis` only parses profiler
artifacts; the flight recorder and multi-host capture shipping need the
inverse — take :class:`~grace_tpu.profiling.trace_analysis.Span` lists
(possibly one per host), merge them, and emit a Chrome-trace JSON that
``parse_chrome_trace`` round-trips **exactly**: device names through
``process_name`` metadata events, lanes through ``thread_name``, the
span scope through the ``args.scope`` key the parser already reads.

Typical shipping path for a multi-host capture::

    spans = merge_host_traces({"host0": spans0, "host1": spans1})
    write_chrome_trace(spans, "EVIDENCE/capture.trace.json.gz")

after which ``load_trace_events`` / ``perf_report`` analyze the merged
per-hop/per-tier spans like any single-host trace.
"""

from __future__ import annotations

import gzip
import json
import os
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

from grace_tpu.profiling.trace_analysis import Span

__all__ = ["chrome_trace_doc", "write_chrome_trace", "merge_host_traces"]


def chrome_trace_doc(spans: Iterable[Span]) -> Dict[str, Any]:
    """Spans → Chrome-trace dict. Deterministic pid/tid assignment (sorted
    device / (device, lane) order) and deterministic event order so
    identical span sets produce byte-identical documents."""
    spans = sorted(spans, key=lambda s: (s.ts, s.device, s.lane, s.name,
                                         s.dur))
    devices = sorted({s.device for s in spans})
    pids = {d: i for i, d in enumerate(devices)}
    lanes = sorted({(s.device, s.lane) for s in spans})
    tids: Dict[Tuple[str, str], int] = {}
    for device, lane in lanes:
        # tids only need to be unique per pid; number within the device.
        tids[(device, lane)] = sum(1 for d, _ in tids if d == device)
    events: List[Dict[str, Any]] = []
    for device in devices:
        events.append({"ph": "M", "name": "process_name",
                       "pid": pids[device], "args": {"name": device}})
    for device, lane in lanes:
        events.append({"ph": "M", "name": "thread_name",
                       "pid": pids[device], "tid": tids[(device, lane)],
                       "args": {"name": lane}})
    for s in spans:
        ev: Dict[str, Any] = {"ph": "X", "name": s.name,
                              "ts": s.ts, "dur": s.dur,
                              "pid": pids[s.device],
                              "tid": tids[(s.device, s.lane)]}
        if s.scope:
            ev["args"] = {"scope": s.scope}
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(spans: Iterable[Span], path: str) -> str:
    """Write spans as a Chrome trace; gzip iff the filename says so
    (matching ``load_trace_events``'s dispatch). Atomic tmp+replace like
    every other evidence writer."""
    doc = chrome_trace_doc(spans)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    payload = json.dumps(doc)
    if path.lower().endswith(".gz"):
        # mtime=0 keeps the archive deterministic for hash-stable evidence.
        with open(tmp, "wb") as f:
            with gzip.GzipFile(fileobj=f, mode="wb", mtime=0) as gz:
                gz.write(payload.encode())
            f.flush()
            os.fsync(f.fileno())
    else:
        with open(tmp, "w") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def merge_host_traces(host_spans: Mapping[str, Iterable[Span]], *,
                      align: bool = True,
                      offsets_us: Optional[Mapping[str, float]] = None
                      ) -> List[Span]:
    """Merge per-host span lists into one timeline.

    Device names get a ``<host>/`` prefix so two hosts' ``TPU:0`` lanes
    stay distinct lanes in the merged per-hop/per-tier view. Hosts have
    no shared clock: ``align=True`` rebases each host so its earliest
    span starts at t=0 (good enough for per-stage attribution, which sums
    durations); pass measured ``offsets_us`` per host instead when a
    clock-sync estimate exists (it wins over ``align``).
    """
    merged: List[Span] = []
    for host in sorted(host_spans):
        spans = list(host_spans[host])
        if not spans:
            continue
        if offsets_us is not None and host in offsets_us:
            shift = float(offsets_us[host])
        elif align:
            shift = -min(s.ts for s in spans)
        else:
            shift = 0.0
        for s in spans:
            device = f"{host}/{s.device}" if host else s.device
            merged.append(Span(name=s.name, ts=s.ts + shift, dur=s.dur,
                               device=device, lane=s.lane, scope=s.scope))
    merged.sort(key=lambda s: (s.ts, s.device, s.lane, s.name))
    return merged
