"""Performance attribution: the read side of the observability stack.

PR 2 made every pipeline stage *writable* into a device trace (canonical
``grace/...`` scopes, :mod:`grace_tpu.telemetry.scopes`); this package
reads the evidence back:

* :mod:`~grace_tpu.profiling.trace_analysis` — parse a ``jax.profiler``
  artifact (``trace.json.gz`` or raw ``xplane.pb``) into a per-stage
  device-time breakdown, a compute-vs-collective split, an **overlap
  fraction** (collective time hidden under compute, from device timelines),
  and step-time percentiles. Pure host-side; runs on a CPU-only box against
  a saved trace.
* :mod:`~grace_tpu.profiling.trace_export` — the write side: spans back
  out as Chrome-trace JSON (``parse_chrome_trace`` round-trips it
  exactly) plus :func:`merge_host_traces` so a multi-host capture ships
  one merged per-hop/per-tier timeline.
* :mod:`~grace_tpu.profiling.recorder` — :class:`ProfileRecorder`, the
  runtime side: step-time percentiles, compile/retrace events (the dynamic
  twin of graft-lint's ``signature_stability`` pass), device-memory
  watermarks, and GraceState footprint accounting checked against the
  codec's expected model — all emitted through the existing telemetry
  sinks.

CLI: ``tools/perf_report.py`` (stage table + overlap % + percentiles +
baseline gating, writes ``PROF_LAST.json``); ``tools/tpu_profile.py``
captures on the chip and reports through the same analyzer offline.
"""

from grace_tpu.profiling.recorder import (ProfileRecorder,
                                          check_state_footprint,
                                          compile_count,
                                          device_memory_watermarks,
                                          expected_state_footprint,
                                          grace_state_footprint)
from grace_tpu.profiling.trace_analysis import (Span, TraceAnalysis,
                                                analyze_spans, analyze_trace,
                                                enrich_spans,
                                                find_latest_trace,
                                                hlo_scope_map,
                                                interval_union_us,
                                                load_trace_events,
                                                overlap_us,
                                                parse_chrome_trace,
                                                parse_xplane)
from grace_tpu.profiling.trace_export import (chrome_trace_doc,
                                              merge_host_traces,
                                              write_chrome_trace)

__all__ = [
    "ProfileRecorder", "check_state_footprint", "compile_count",
    "device_memory_watermarks", "expected_state_footprint",
    "grace_state_footprint",
    "Span", "TraceAnalysis", "analyze_spans", "analyze_trace",
    "enrich_spans", "find_latest_trace", "hlo_scope_map",
    "interval_union_us", "load_trace_events", "overlap_us",
    "parse_chrome_trace", "parse_xplane",
    "chrome_trace_doc", "merge_host_traces", "write_chrome_trace",
]
