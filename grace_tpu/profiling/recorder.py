"""Runtime performance recorder: step times, retraces, memory watermarks.

:class:`ProfileRecorder` is the runtime twin of the offline trace analyzer
(:mod:`grace_tpu.profiling.trace_analysis`) and the dynamic twin of
graft-lint's ``signature_stability`` pass: where the static pass proves the
state signature is a fixed point *of the traced update*, the recorder
watches the live jit cache and catches whatever escapes static analysis
(a data-dependent shape, a host wrapper rebuilding closures) the moment it
recompiles. It promotes :class:`grace_tpu.utils.profiling.StepTimer` from a
bench-local helper into the long-run observability stack:

* **step-time percentiles** (mean/p50/p90/p99/max over the steady window),
  emitted every flush as ``perf_step_times`` records — stamped with
  ``sync_missing`` when the timer only ever measured async dispatch, so a
  meaningless number carries its own caveat;
* **compile/retrace events** — ``perf_compile`` for the first observed
  compile, ``perf_retrace`` whenever the step function's jit cache grows
  afterwards (each retrace silently doubles compile memory and stalls the
  device for seconds; a per-step retrace is the weak-type closure-leak bug
  class);
* **device-memory watermarks** (``perf_memory``: ``bytes_in_use`` /
  ``peak_bytes_in_use`` from the runtime's allocator stats, max across
  local devices; silently absent on backends without stats, e.g. CPU);
* **GraceState footprint accounting** (``perf_state_footprint``): the
  measured mem/comp/telem bytes of the live state, checked against the
  codec's *expected* footprint (the abstract shape of ``transform.init``
  — exact by construction, so any mismatch means the live state was built
  under a different config than the one being reported).

All records flow through the same :class:`grace_tpu.telemetry.Sink` funnel
as the telemetry reader and the guard/consensus monitors, so one JSONL
artifact carries the whole run — ``tools/telemetry_report.py`` renders the
``perf_*`` records in their own section.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import numpy as np

from grace_tpu.utils.profiling import StepTimer

__all__ = ["ProfileRecorder", "compile_count", "device_memory_watermarks",
           "grace_state_footprint", "expected_state_footprint",
           "check_state_footprint"]


def compile_count(step_fn) -> Optional[int]:
    """Total compiled variants behind a step function, or None when the
    callable exposes no jit cache. Understands both a raw ``jax.jit``
    wrapper (``_cache_size``) and the lazy-spec wrapper
    ``grace_tpu.train`` returns (``jit_cache`` dict of jitted fns)."""
    cache = getattr(step_fn, "jit_cache", None)
    if cache is not None:
        total = 0
        for fn in cache.values():
            sub = compile_count(fn)
            if sub is None:
                return None
            total += sub
        return total
    size = getattr(step_fn, "_cache_size", None)
    if callable(size):
        try:
            return int(size())
        except Exception:
            return None
    return None


def device_memory_watermarks(devices=None) -> Optional[Dict[str, int]]:
    """Max ``bytes_in_use`` / ``peak_bytes_in_use`` across local devices,
    from the runtime allocator's ``memory_stats()``. None when no local
    device reports stats (CPU backends)."""
    devices = list(devices) if devices is not None else jax.local_devices()
    in_use, peak = [], []
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            pass
        if not stats:
            continue
        if "bytes_in_use" in stats:
            in_use.append(int(stats["bytes_in_use"]))
        if "peak_bytes_in_use" in stats:
            peak.append(int(stats["peak_bytes_in_use"]))
    if not in_use and not peak:
        return None
    out: Dict[str, int] = {"n_devices": len(devices)}
    if in_use:
        out["bytes_in_use"] = max(in_use)
    if peak:
        out["peak_bytes_in_use"] = max(peak)
    return out


# ---------------------------------------------------------------------------
# GraceState footprint accounting
# ---------------------------------------------------------------------------

def _leaf_nbytes(leaf) -> int:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


def _tree_nbytes(tree) -> int:
    return sum(_leaf_nbytes(l) for l in jax.tree_util.tree_leaves(tree))


def grace_state_footprint(tree) -> Dict[str, int]:
    """Bytes held by every :class:`~grace_tpu.transform.GraceState` in
    ``tree``, grouped by component: ``mem`` (error-feedback residuals),
    ``comp`` (compressor state, e.g. PowerSGD Q), ``telem`` (the on-device
    metric ring), and ``bookkeeping`` (count/rng/fallback/audit scalars).
    Works on live arrays and on ``jax.eval_shape`` structures alike —
    that symmetry is what :func:`check_state_footprint` exploits. On a
    global (train-loop) state the mem/comp/telem leaves carry their sharded
    world axis, so the numbers are whole-mesh bytes, not per-device."""
    from grace_tpu.transform import GraceState

    mem = comp = telem = book = 0
    found = 0

    def visit(node):
        nonlocal mem, comp, telem, book, found
        if isinstance(node, GraceState):
            found += 1
            mem += _tree_nbytes(node.mem)
            comp += _tree_nbytes(node.comp)
            # The graft-watch summary ring is telemetry state: per-rank
            # sharded like the metric ring, world-independent row shape,
            # so it scales with `world` in expected_state_footprint
            # exactly like telem does.
            telem += _tree_nbytes((node.telem, node.watch))
            book += _tree_nbytes((node.count, node.rng_key, node.fallback,
                                  node.audit, node.adapt))
        return node

    jax.tree_util.tree_map(visit, tree,
                           is_leaf=lambda n: isinstance(n, GraceState))
    return {"grace_states": found,
            "mem_bytes": mem, "comp_bytes": comp, "telem_bytes": telem,
            "bookkeeping_bytes": book,
            "total_bytes": mem + comp + telem + book}


def expected_state_footprint(grace_or_tx, params,
                             world: int = 1) -> Dict[str, int]:
    """The codec's expected GraceState footprint for ``params``: the
    abstract shapes of ``transform.init`` (no allocation — safe on a
    device-free box), with the per-rank-sharded components (mem/comp/telem)
    scaled to ``world`` ranks to match the global layout
    ``init_train_state`` builds. ``grace_or_tx`` is a ``Grace`` bundle or
    a ready ``optax.GradientTransformation``."""
    tx = (grace_or_tx.transform(seed=0)
          if hasattr(grace_or_tx, "transform") else grace_or_tx)
    fp = grace_state_footprint(jax.eval_shape(tx.init, params))
    for key in ("mem_bytes", "comp_bytes", "telem_bytes"):
        fp[key] *= world
    fp["total_bytes"] = (fp["mem_bytes"] + fp["comp_bytes"]
                         + fp["telem_bytes"] + fp["bookkeeping_bytes"])
    return fp


def check_state_footprint(state, grace_or_tx, params,
                          world: int = 1) -> Dict[str, Any]:
    """Live GraceState bytes vs the expected model. ``matches`` compares
    the three per-codec components exactly — the model is the abstract
    init shape, so a mismatch means the live state was built under a
    different codec/fusion/telemetry config than the one being reported
    (the bug class the bench resume gate exists for)."""
    live = grace_state_footprint(state)
    model = expected_state_footprint(grace_or_tx, params, world=world)
    matches = all(live[k] == model[k]
                  for k in ("mem_bytes", "comp_bytes", "telem_bytes"))
    return {"live": live, "model": model, "matches": matches}


# ---------------------------------------------------------------------------
# the recorder
# ---------------------------------------------------------------------------

class ProfileRecorder:
    """Record step times, retraces, and memory through a telemetry sink.

    Usage::

        rec = ProfileRecorder(sink, every=25, step_fn=step)
        for i, batch in enumerate(batches):
            with rec.step():
                state, loss = step(state, batch)
                rec.sync_on(loss)
            rec.update(i)
        rec.flush(len(batches) - 1)

    ``step_fn`` (optional) enables retrace detection via
    :func:`compile_count`; without it only timing/memory records are
    emitted. The recorder never touches the device between flushes — step
    timing is host wall-clock around the timer's sync fetch, memory stats
    are an allocator query, and the retrace probe reads a host-side cache
    size — so it is safe on the hot path (contrast the host callbacks
    graft-lint's pass 4 rejects).
    """

    def __init__(self, sink=None, every: int = 20, warmup: int = 2,
                 step_fn=None, percentiles=(50, 90, 99)):
        if every < 1:
            raise ValueError(f"flush interval must be >= 1; got {every}")
        self.sink = sink
        self.every = every
        self.percentiles = tuple(percentiles)
        self.timer = StepTimer(warmup=warmup)
        self.retraces = 0        # cache growth events after the first compile
        self.flushes = 0
        self._step_fn = step_fn
        self._compiles: Optional[int] = None

    # -- timing (delegates to the promoted StepTimer) -----------------------
    def step(self):
        return self.timer.step()

    def sync_on(self, out) -> None:
        self.timer.sync_on(out)

    # -- per-iteration hook -------------------------------------------------
    def update(self, step: int) -> List[dict]:
        """Call once per loop iteration (after the step). Checks the jit
        cache every iteration — a retrace must be attributed to the step
        that caused it, not to a flush boundary — and emits the windowed
        records on every ``every``-th call."""
        records = self._check_retrace(step)
        if (step + 1) % self.every == 0:
            records.extend(self.flush(step))
        return records

    def _check_retrace(self, step: int) -> List[dict]:
        if self._step_fn is None:
            return []
        count = compile_count(self._step_fn)
        if count is None:
            return []
        records: List[dict] = []
        if self._compiles is None:
            self._compiles = count
            if count > 0:
                records.append({"event": "perf_compile", "step": step,
                                "cache_size": count})
        elif count > self._compiles:
            self.retraces += count - self._compiles
            self._compiles = count
            records.append({"event": "perf_retrace", "step": step,
                            "cache_size": count,
                            "retraces": self.retraces})
        self._emit(records)
        return records

    def flush(self, step: int) -> List[dict]:
        """Emit the windowed records: step-time percentiles and (when the
        backend reports allocator stats) the memory watermark."""
        records: List[dict] = []
        if len(self.timer):
            arr = self.timer.steady * 1e3
            rec = {"event": "perf_step_times", "step": step,
                   "n_steps": int(arr.size),
                   "mean_ms": float(arr.mean()),
                   "max_ms": float(arr.max())}
            for q in self.percentiles:
                rec[f"p{q:g}_ms"] = float(np.percentile(arr, q))
            if self.timer.measured_async_dispatch:
                # dispatch-only timings: the number is not a step time
                rec["sync_missing"] = True
            if self.timer.failed_steps:
                rec["failed_steps"] = self.timer.failed_steps
            records.append(rec)
        mem = device_memory_watermarks()
        if mem is not None:
            records.append({"event": "perf_memory", "step": step, **mem})
        self.flushes += 1
        self._emit(records)
        return records

    def record_state_footprint(self, state, grace_or_tx=None, params=None,
                               world: int = 1, step: int = -1) -> dict:
        """One-shot GraceState footprint record (the footprint is fixed at
        init, so once per run is enough). With ``grace_or_tx`` + ``params``
        the live bytes are checked against the expected model and the
        record carries ``footprint_matches``."""
        rec: Dict[str, Any] = {"event": "perf_state_footprint", "step": step}
        if grace_or_tx is not None and params is not None:
            checked = check_state_footprint(state, grace_or_tx, params,
                                            world=world)
            rec.update(checked["live"])
            rec.update({f"model_{k}": v for k, v in checked["model"].items()
                        if k.endswith("_bytes")})
            rec["footprint_matches"] = checked["matches"]
        else:
            rec.update(grace_state_footprint(state))
        self._emit([rec])
        return rec

    def _emit(self, records: List[dict]) -> None:
        if self.sink is not None:
            for rec in records:
                self.sink.write(rec)

    def close(self) -> None:
        if self.sink is not None:
            self.sink.close()
