"""Profiler-trace analysis: attribute device time to pipeline stages.

The write side of observability has existed since PR 2: every pipeline
stage runs under a canonical ``grace/...`` scope
(:mod:`grace_tpu.telemetry.scopes`), so ``jax.profiler`` traces carry the
stage vocabulary in their op names. This module is the READ side: it parses
a profiler artifact back into spans and answers the questions the ROADMAP's
perf arc is blocked on —

* **where did the step's device time go, per stage?** Each device span is
  attributed to a canonical stage via the same longest-prefix match the
  static auditor uses (:func:`grace_tpu.telemetry.scopes.match_stage`), and
  charged its *self* time (child spans subtracted), so the per-stage table
  sums exactly to the total device time;
* **compute vs collective split** — op-name classification of the XLA
  collective families (all-gather/all-reduce/all-to-all/collective-permute/
  reduce-scatter and their fusion spellings);
* **overlap fraction** — the share of collective time hidden under
  concurrent compute on the same device, computed from interval unions of
  the *device* timelines (NOT host wall-clock: host timing can neither see
  that a collective ran under the backward pass nor avoid counting dispatch
  gaps — see IMPLEMENTING.md "Per-link wire model & overlap"). This is the
  before/after number ROADMAP item 2 (bucketed overlap, Pallas fusion)
  needs, and the measured answer to the projection model's documented
  "assumes NO overlap" caveat;
* **step-time percentiles** from the trace's step markers.

Two input formats, one span model:

* ``*.trace.json.gz`` / ``*.json`` — the Chrome-trace-format export every
  ``jax.profiler.trace`` capture writes (the format the old ad-hoc
  ``tpu_profile --report`` summarized). Fully supported.
* ``*.xplane.pb`` — the raw XSpace protobuf. Decoded with a small
  schema-pinned reader (:data:`_XPLANE_SCHEMA`; pure stdlib, mirroring the
  hand-encoded protos of :class:`~grace_tpu.telemetry.sinks.TensorBoardSink`)
  — best effort against the stable upstream field numbering.

Everything here is pure host-side stdlib + numpy: it runs on a CPU-only box
with no devices, against a checked-in canned trace
(``tests/data/perf_trace.json.gz``), which is how the whole module is
tested and how ``tools/perf_report.py`` gates CI.
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import os
import struct
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from grace_tpu.telemetry.scopes import match_stage

__all__ = ["Span", "TraceAnalysis", "load_trace_events", "parse_chrome_trace",
           "parse_xplane", "analyze_trace", "analyze_spans",
           "hlo_scope_map", "enrich_spans",
           "interval_union_us", "overlap_us", "find_latest_trace",
           "UNATTRIBUTED", "STEP_LANE"]

# Stage bucket for device spans outside the grace/... vocabulary (the model
# forward/backward XLA fusions that run under no named scope, framework
# infeed, etc.). Kept explicit so the stage table still sums to the total.
UNATTRIBUTED = "(unattributed)"

# Lane (thread) name the XLA profiler uses for per-step markers.
STEP_LANE = "Steps"

# Op-name substrings that mark a device span as wire time. XLA spells the
# collectives with dashes in HLO op names (all-gather.3, collective-permute-
# start) and jax spells the primitives with underscores in scope names —
# match both. "Fusion" never matches: a fused collective keeps its
# collective op name as a prefix in XLA naming.
_COLLECTIVE_TOKENS = (
    "all-gather", "all_gather", "all-reduce", "all_reduce", "allreduce",
    "all-to-all", "all_to_all", "collective-permute", "collective_permute",
    "ppermute", "reduce-scatter", "reduce_scatter", "psum",
    "collective-broadcast", "send-done", "recv-done",
)


@dataclasses.dataclass(frozen=True)
class Span:
    """One complete event on one timeline: ``[ts, ts+dur)`` microseconds."""

    name: str
    ts: float                 # µs since trace epoch
    dur: float                # µs
    device: str = ""          # process name, e.g. "/device:TPU:0"
    lane: str = ""            # thread name, e.g. "XLA Ops" / "Steps"
    scope: str = ""           # extra scope path (args metadata), if any

    @property
    def end(self) -> float:
        return self.ts + self.dur

    def stage(self) -> str:
        """Canonical grace stage of this span (name first, scope second)."""
        return match_stage(self.name) or match_stage(self.scope)

    def is_collective(self) -> bool:
        text = f"{self.name} {self.scope}".lower()
        return any(tok in text for tok in _COLLECTIVE_TOKENS)


# ---------------------------------------------------------------------------
# Chrome trace format (trace.json.gz)
# ---------------------------------------------------------------------------

def parse_chrome_trace(doc: Mapping) -> List[Span]:
    """Chrome-trace-format dict → spans, with pid/tid names resolved from
    the ``process_name``/``thread_name`` metadata events."""
    events = doc.get("traceEvents", [])
    pid_names: Dict[object, str] = {}
    tid_names: Dict[Tuple[object, object], str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        args = e.get("args") or {}
        if e.get("name") == "process_name":
            pid_names[e.get("pid")] = str(args.get("name", e.get("pid")))
        elif e.get("name") == "thread_name":
            tid_names[(e.get("pid"), e.get("tid"))] = str(
                args.get("name", e.get("tid")))
    spans: List[Span] = []
    for e in events:
        if e.get("ph") != "X" or not e.get("dur"):
            continue
        pid, tid = e.get("pid"), e.get("tid")
        args = e.get("args") or {}
        # named_scope metadata surfaces in different arg keys across
        # profiler versions (long_name carries the full HLO metadata path).
        scope = " ".join(str(v) for k, v in sorted(args.items())
                         if isinstance(v, str)
                         and k in ("name", "long_name", "tf_op", "scope",
                                   "hlo_op", "group_name"))
        spans.append(Span(name=str(e.get("name", "")),
                          ts=float(e["ts"]), dur=float(e["dur"]),
                          device=pid_names.get(pid, f"pid {pid}"),
                          lane=tid_names.get((pid, tid), f"tid {tid}"),
                          scope=scope))
    return spans


# ---------------------------------------------------------------------------
# XSpace protobuf (xplane.pb) — schema-pinned minimal decoder
# ---------------------------------------------------------------------------

# Field numbers of the upstream xplane.proto messages this reader walks.
# ONE table shared with the test-side encoder (tests/test_profiling.py
# round-trips a hand-built XSpace through it), so reader and fixture can
# never disagree; against real captures it is best-effort on the stable
# upstream numbering.
_XPLANE_SCHEMA = {
    "XSpace": {"planes": 1},
    "XPlane": {"id": 1, "name": 2, "lines": 3, "event_metadata": 4,
               "stat_metadata": 5},
    "XLine": {"id": 1, "name": 2, "timestamp_ns": 3, "events": 4,
              "duration_ps": 9, "display_id": 10, "display_name": 11},
    "XEvent": {"metadata_id": 1, "offset_ps": 2, "duration_ps": 3,
               "stats": 4},
    "XEventMetadata": {"id": 1, "name": 2, "display_name": 4},
    "XStat": {"metadata_id": 1, "str_value": 5},
    "map_entry": {"key": 1, "value": 2},
}


def _iter_proto_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over one serialized message.
    Varints yield ints; length-delimited yield bytes; fixed widths ints."""
    i, n = 0, len(buf)
    while i < n:
        tag = 0
        shift = 0
        while True:
            b = buf[i]
            i += 1
            tag |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        field, wire = tag >> 3, tag & 0x7
        if wire == 0:                      # varint
            val = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                val |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            yield field, wire, val
        elif wire == 2:                    # length-delimited
            ln = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            yield field, wire, buf[i:i + ln]
            i += ln
        elif wire == 1:                    # 64-bit
            yield field, wire, struct.unpack("<Q", buf[i:i + 8])[0]
            i += 8
        elif wire == 5:                    # 32-bit
            yield field, wire, struct.unpack("<I", buf[i:i + 4])[0]
            i += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wire} "
                             f"(field {field}) — not an XSpace?")


def _proto_dict(buf: bytes) -> Dict[int, list]:
    out: Dict[int, list] = {}
    for field, _wire, val in _iter_proto_fields(buf):
        out.setdefault(field, []).append(val)
    return out


def _first(d: Dict[int, list], field: int, default=None):
    vals = d.get(field)
    return vals[0] if vals else default


def hlo_scope_map(data: bytes) -> Dict[str, str]:
    """Instruction-name → grace-scope joins harvested from the serialized
    HLO protos an xplane's ``/host:metadata`` plane embeds.

    Some runtimes (XLA:CPU notably) export execution events under bare HLO
    instruction names (``all-gather.11``, ``copy.203``) with no op-name
    metadata — the ``named_scope`` paths live only inside the HLO proto's
    per-instruction ``metadata.op_name``. Rather than pin the full
    HloModuleProto schema, this walks every nested message generically and
    pairs each message's field-1 identifier (the instruction name, by HLO
    proto convention) with the nearest descendant string containing
    ``grace/`` — exactly the vocabulary :func:`match_stage` consumes, so a
    mis-paired non-grace string can never pollute attribution. Best-effort
    by construction: an empty map just leaves spans unattributed.
    """
    out: Dict[str, str] = {}

    def walk(buf: bytes, owner: Optional[str], depth: int) -> None:
        if depth > 40:
            return
        try:
            fields = _proto_dict(buf)
        except Exception:
            return
        name, name_bytes = owner, None
        v = fields.get(1)
        if v and isinstance(v[0], bytes) and 0 < len(v[0]) < 128:
            try:
                s = v[0].decode()
                if s and s.isascii() and all(c.isalnum() or c in "._-"
                                             for c in s):
                    name, name_bytes = s, v[0]
            except UnicodeDecodeError:
                pass
        for vals in fields.values():
            for val in vals:
                if not isinstance(val, bytes) or val is name_bytes \
                        or b"grace/" not in val:
                    continue
                try:
                    txt = val.decode()
                except UnicodeDecodeError:
                    txt = None
                if txt is not None and "grace/" in txt and len(txt) < 512 \
                        and "\n" not in txt:
                    if name is not None:
                        out.setdefault(name, txt)
                else:
                    walk(val, name, depth + 1)

    walk(data, None, 0)
    return out


def enrich_spans(spans: List[Span],
                 scope_map: Mapping[str, str]) -> List[Span]:
    """Attach scopes from an instruction-name → scope map
    (:func:`hlo_scope_map`) to spans that attribute to no stage yet.
    An existing scope is appended to, not replaced (Chrome CPU exports
    stuff the bare op name into ``args.name``, which carries no stage);
    spans already attributable or finding no mapping pass through."""
    if not scope_map:
        return spans
    return [dataclasses.replace(
                s, scope=f"{s.scope} {scope_map[s.name]}".strip())
            if not s.stage() and s.name in scope_map else s
            for s in spans]


def parse_xplane(data: bytes) -> List[Span]:
    """Serialized XSpace → spans (device = plane name, lane = line name).
    When the space embeds HLO protos carrying ``grace/`` op names (the
    XLA:CPU layout), spans are enriched via :func:`hlo_scope_map`."""
    S = _XPLANE_SCHEMA
    spans: List[Span] = []
    space = _proto_dict(data)
    for plane_buf in space.get(S["XSpace"]["planes"], []):
        plane = _proto_dict(plane_buf)
        device = _first(plane, S["XPlane"]["name"], b"").decode(
            "utf-8", "replace")
        ev_meta: Dict[int, str] = {}
        for entry_buf in plane.get(S["XPlane"]["event_metadata"], []):
            entry = _proto_dict(entry_buf)
            key = _first(entry, S["map_entry"]["key"], 0)
            md_buf = _first(entry, S["map_entry"]["value"], b"")
            md = _proto_dict(md_buf)
            name = _first(md, S["XEventMetadata"]["name"], b"")
            ev_meta[int(key)] = name.decode("utf-8", "replace")
        for line_buf in plane.get(S["XPlane"]["lines"], []):
            line = _proto_dict(line_buf)
            lane = (_first(line, S["XLine"]["display_name"])
                    or _first(line, S["XLine"]["name"], b"")).decode(
                        "utf-8", "replace")
            base_ns = int(_first(line, S["XLine"]["timestamp_ns"], 0))
            for ev_buf in line.get(S["XLine"]["events"], []):
                ev = _proto_dict(ev_buf)
                md_id = int(_first(ev, S["XEvent"]["metadata_id"], 0))
                offset_ps = int(_first(ev, S["XEvent"]["offset_ps"], 0))
                dur_ps = int(_first(ev, S["XEvent"]["duration_ps"], 0))
                if dur_ps <= 0:
                    continue
                spans.append(Span(
                    name=ev_meta.get(md_id, f"event {md_id}"),
                    ts=base_ns * 1e-3 + offset_ps * 1e-6,   # → µs
                    dur=dur_ps * 1e-6,
                    device=device, lane=lane))
    if b"grace/" in data and not any(s.stage() for s in spans):
        spans = enrich_spans(spans, hlo_scope_map(data))
    return spans


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def load_trace_events(path: str) -> List[Span]:
    """Load spans from a profiler artifact, dispatching on the filename
    (``.json``/``.json.gz`` → Chrome trace; ``.pb``/``.xplane`` → XSpace)."""
    lower = path.lower()
    if lower.endswith(".pb") or ".xplane" in lower:
        with open(path, "rb") as f:
            return parse_xplane(f.read())
    opener = gzip.open if lower.endswith(".gz") else open
    with opener(path, "rt") as f:
        return parse_chrome_trace(json.load(f))


def find_latest_trace(logdir: str) -> Optional[str]:
    """Newest profiler artifact under ``logdir`` (the layout
    ``jax.profiler.trace`` writes: ``plugins/profile/<run>/…``)."""
    paths = []
    for pattern in ("**/*.trace.json.gz", "**/*.xplane.pb"):
        paths.extend(glob.glob(os.path.join(logdir, pattern),
                               recursive=True))
    return max(paths, key=os.path.getmtime) if paths else None


# ---------------------------------------------------------------------------
# interval math (all µs)
# ---------------------------------------------------------------------------

def interval_union_us(intervals: Iterable[Tuple[float, float]]
                      ) -> List[Tuple[float, float]]:
    """Merge ``(start, end)`` intervals into a disjoint sorted union."""
    ivs = sorted((s, e) for s, e in intervals if e > s)
    out: List[Tuple[float, float]] = []
    for s, e in ivs:
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _measure(union: Sequence[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in union)


def overlap_us(a: Sequence[Tuple[float, float]],
               b: Sequence[Tuple[float, float]]) -> float:
    """Measure of the intersection of two interval unions (each already
    disjoint + sorted, as :func:`interval_union_us` returns)."""
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def _self_times(spans: List[Span]) -> List[float]:
    """Self time of each span (dur minus time covered by nested children on
    the same timeline). Chrome-trace complete events on one thread nest
    properly; a malformed partial overlap clamps at zero rather than going
    negative. Per-stage sums of self time add up exactly to the union
    measure of the lane — the invariant that makes the stage table sum to
    the total."""
    order = sorted(range(len(spans)),
                   key=lambda i: (spans[i].ts, -spans[i].dur))
    child = [0.0] * len(spans)
    stack: List[int] = []
    for i in order:
        s = spans[i]
        while stack and s.ts >= spans[stack[-1]].end - 1e-9:
            stack.pop()
        if stack:
            child[stack[-1]] += s.dur
        stack.append(i)
    return [max(0.0, spans[i].dur - child[i]) for i in range(len(spans))]


# ---------------------------------------------------------------------------
# analysis
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class TraceAnalysis:
    """Stage attribution + overlap + step stats of one profiler trace."""

    path: Optional[str]
    n_spans: int
    devices: List[str]
    device_lanes_detected: bool
    total_us: float                       # total device self time
    stage_us: Dict[str, float]            # canonical stage → self µs
    compute_us: float
    collective_us: float
    overlap_us: float                     # collective ∩ compute, device time
    step_times_us: List[float]

    @property
    def overlap_fraction(self) -> Optional[float]:
        """Share of collective device time hidden under concurrent compute
        on the same device; None when the trace has no collective time."""
        if self.collective_us <= 0.0:
            return None
        return self.overlap_us / self.collective_us

    def step_percentiles_ms(self) -> Optional[Dict[str, float]]:
        if not self.step_times_us:
            return None
        arr = np.asarray(self.step_times_us) * 1e-3
        return {"n": len(self.step_times_us),
                "mean_ms": float(arr.mean()),
                "p50_ms": float(np.percentile(arr, 50)),
                "p90_ms": float(np.percentile(arr, 90)),
                "p99_ms": float(np.percentile(arr, 99)),
                "max_ms": float(arr.max())}

    def as_dict(self) -> dict:
        return {
            "trace": self.path,
            "n_spans": self.n_spans,
            "devices": self.devices,
            "device_lanes_detected": self.device_lanes_detected,
            "total_device_ms": round(self.total_us * 1e-3, 6),
            "stages_ms": {k: round(v * 1e-3, 6)
                          for k, v in sorted(self.stage_us.items(),
                                             key=lambda kv: -kv[1])},
            "compute_ms": round(self.compute_us * 1e-3, 6),
            "collective_ms": round(self.collective_us * 1e-3, 6),
            "overlap_ms": round(self.overlap_us * 1e-3, 6),
            "overlap_fraction": (None if self.overlap_fraction is None
                                 else round(self.overlap_fraction, 6)),
            "step_times": self.step_percentiles_ms(),
        }

    def render(self) -> str:
        out = []
        dev = ", ".join(self.devices) or "(no device lanes — all spans)"
        out.append(f"devices: {dev}")
        out.append(f"spans: {self.n_spans}   total device time: "
                   f"{self.total_us / 1e3:.3f} ms")
        out.append("")
        out.append(f"  {'stage':<28s}{'ms':>12s}{'share':>9s}")
        for name, us in sorted(self.stage_us.items(), key=lambda kv: -kv[1]):
            share = us / self.total_us if self.total_us else 0.0
            out.append(f"  {name:<28s}{us / 1e3:>12.3f}{share:>8.1%}")
        out.append(f"  {'TOTAL':<28s}{self.total_us / 1e3:>12.3f}"
                   f"{'100.0%':>9s}")
        out.append("")
        out.append(f"  compute: {self.compute_us / 1e3:.3f} ms   "
                   f"collective: {self.collective_us / 1e3:.3f} ms")
        if self.overlap_fraction is None:
            out.append("  overlap: n/a (no collective time in trace)")
        else:
            out.append(
                f"  overlap: {self.overlap_us / 1e3:.3f} ms of collective "
                f"time hidden under compute — overlap fraction "
                f"{self.overlap_fraction:.1%} (device timelines; the bench "
                "projection model assumes 0%)")
        sp = self.step_percentiles_ms()
        if sp:
            out.append(f"  steps: n={sp['n']}  mean {sp['mean_ms']:.3f} ms  "
                       f"p50 {sp['p50_ms']:.3f}  p90 {sp['p90_ms']:.3f}  "
                       f"p99 {sp['p99_ms']:.3f}  max {sp['max_ms']:.3f}")
        return "\n".join(out)


def _is_device(name: str) -> bool:
    low = name.lower()
    return "/device:" in low or "tpu" in low or "gpu" in low


def analyze_spans(spans: List[Span],
                  path: Optional[str] = None) -> TraceAnalysis:
    """Attribute a span list. Device lanes are processes named like
    ``/device:TPU:0``; when the trace marks none (some CPU captures), every
    lane is analyzed and the result says so. The ``Steps`` lane provides
    step-time samples and is excluded from op attribution (its markers
    *cover* the ops; charging both would double-count)."""
    device_spans = [s for s in spans if _is_device(s.device)]
    detected = bool(device_spans)
    if not detected:
        device_spans = list(spans)
    step_times = [s.dur for s in device_spans if s.lane == STEP_LANE]
    op_spans = [s for s in device_spans if s.lane != STEP_LANE]

    by_lane: Dict[Tuple[str, str], List[Span]] = {}
    for s in op_spans:
        by_lane.setdefault((s.device, s.lane), []).append(s)

    stage_us: Dict[str, float] = {}
    total = 0.0
    coll_by_device: Dict[str, List[Tuple[float, float]]] = {}
    comp_by_device: Dict[str, List[Tuple[float, float]]] = {}
    for (device, _lane), lane_spans in by_lane.items():
        selfs = _self_times(lane_spans)
        for s, self_us in zip(lane_spans, selfs):
            stage = s.stage() or UNATTRIBUTED
            stage_us[stage] = stage_us.get(stage, 0.0) + self_us
            total += self_us
            bucket = (coll_by_device if s.is_collective()
                      else comp_by_device)
            bucket.setdefault(device, []).append((s.ts, s.end))

    collective = overlap = compute = 0.0
    for device in set(coll_by_device) | set(comp_by_device):
        cu = interval_union_us(coll_by_device.get(device, []))
        pu = interval_union_us(comp_by_device.get(device, []))
        collective += _measure(cu)
        compute += _measure(pu)
        overlap += overlap_us(cu, pu)

    return TraceAnalysis(
        path=path,
        n_spans=len(spans),
        devices=sorted({s.device for s in device_spans}),
        device_lanes_detected=detected,
        total_us=total,
        stage_us=stage_us,
        compute_us=compute,
        collective_us=collective,
        overlap_us=overlap,
        step_times_us=step_times)


def analyze_trace(path: str) -> TraceAnalysis:
    """Load + analyze one profiler artifact (see :func:`load_trace_events`);
    pass a directory to analyze its newest capture. A Chrome-trace export
    whose op names carry no grace scopes (the XLA:CPU layout) is enriched
    from a sibling ``xplane.pb``'s embedded HLO metadata when one exists."""
    if os.path.isdir(path):
        found = find_latest_trace(path)
        if found is None:
            raise FileNotFoundError(
                f"no *.trace.json.gz / *.xplane.pb under {path}")
        path = found
    spans = load_trace_events(path)
    if not any(s.stage() for s in spans):
        siblings = glob.glob(os.path.join(os.path.dirname(path),
                                          "*.xplane.pb"))
        if siblings:
            with open(siblings[0], "rb") as f:
                spans = enrich_spans(spans, hlo_scope_map(f.read()))
    return analyze_spans(spans, path=path)
