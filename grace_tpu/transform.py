"""Optax integration: compressed gradient exchange as a GradientTransformation.

This replaces the reference's entire Horovod patch surface
(patch_files/horovod/torch/__init__.py:46-201 `_DistributedOptimizer`,
patch_files/horovod/tensorflow/__init__.py:190-205 grads fn, …): instead of
monkey-patching a framework optimizer with per-parameter backward hooks, the
whole 6-stage GRACE pipeline is an `optax.GradientTransformation` that slots
into any optax chain:

    tx = optax.chain(grace_transform(compressor, memory, communicator),
                     optax.sgd(0.1))

``update`` must run where the communicator's mesh axis is bound — i.e.
inside `shard_map`/`pjit` (see grace_tpu.train.make_train_step). Every
parameter's compensate→compress→update→exchange is traced into ONE XLA
program — the reference's per-parameter Python loop over world_size × n_params
decompressions (SURVEY.md §3.1 hot loop) disappears into the compiler.

State layout: ``GraceState(count, rng_key, mem, comp, fallback, telem,
audit, watch)``
where ``mem``/``comp`` are tuples aligned with the flattened gradient leaves,
``fallback`` is the replicated resilience health flag (see
``grace_transform(escape=...)``), ``telem`` is the optional on-device
telemetry ring (``grace_transform(telemetry=...)``; None when telemetry is
off, so the default state is unchanged), ``audit`` is the optional
replicated consensus-audit bookkeeping (``grace_transform(consensus=...)``;
see :mod:`grace_tpu.resilience.consensus`), and ``watch`` is the optional
per-rank graft-watch summary ring (``grace_transform(watch=...)``; see
:mod:`grace_tpu.telemetry.aggregate`). The rng key is
replicated across ranks, so per-(step, leaf) keys derived via ``fold_in`` are
rank-identical — the explicit contract RandomK/PowerSGD rely on (the
reference relied on global-seed side effects, grace_dl/dist/compressor/
randomk.py:26-29).

**Memory/compressor state is per-rank data** — each worker accumulates its
own residual, exactly as the reference's per-process dicts do
(grace_dl/dist/memory/residual.py:6-20). In the global (outside-shard_map)
view, every ``mem``/``comp`` leaf therefore carries a leading world axis
sharded over the mesh: global shape ``(world, *leaf_shape)``, one row per
rank. ``add_world_axis``/``strip_world_axis`` convert between that layout
and the per-device view used inside the transform, and
``partition_specs`` produces the matching `PartitionSpec` pytree
(``P(axis)`` for mem/comp leaves, ``P()`` for everything else). This makes
residual state an honest sharded array — checkpoints capture every rank's
error feedback, not whichever replica the host happened to read.
"""

from __future__ import annotations

import dataclasses
import fnmatch
from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from grace_tpu.core import (Communicator, Compressor, DEFAULT_AXIS,
                            LinkBytes, Memory, State, Topology, axis_size,
                            negotiation_bytes_for)
from grace_tpu.telemetry.aggregate import (normalize_watch,
                                           watch_gather_bytes, watch_init,
                                           watch_record)
from grace_tpu.telemetry.scopes import (STAGE_BUCKET, STAGE_TELEMETRY,
                                        STAGE_WATCH, trace_stage)
from grace_tpu.telemetry.state import (TelemetryConfig, telemetry_init,
                                       telemetry_record)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """The transform's view of the device mesh: a data-parallel axis plus
    an optional FSDP (sharded-model) axis.

    Pure data parallelism — the only layout the repo spoke until the
    sharded-model track — is the 1-axis degenerate case
    (``fsdp_axis=None``), and every ``axis_name: str`` call site keeps
    working via :meth:`normalize`. With ``fsdp_axis`` set, the training
    step runs inside ``shard_map`` over a 2-D ``dp×fsdp`` mesh:

    * **params and optimizer state are sharded over** ``fsdp_axis`` (the
      caller's ``param_specs`` say how — typically embeddings/weights
      split a dimension, LayerNorm/bias stay replicated), so each device
      holds and updates only its *shard* of the model;
    * **the gradient each device hands the grace transform is the
      per-shard gradient**, and the compressed collective — the
      communicator, whose ``axis_name`` must equal ``dp_axis`` — is the
      per-shard reduce over the dp axis. ``lax`` collectives over
      ``dp_axis`` inside a 2-D mesh operate within each fsdp shard's dp
      group automatically, which is exactly the semantics FSDP needs;
    * **GraceState mem/comp/telem/watch leaves shard over dp per fsdp
      shard**: the global layout's leading world axis spans the dp×fsdp
      *product* (``partition_specs`` emits ``P((dp, fsdp))``), so each
      device's error-feedback residual covers exactly its own shard's
      gradient — residuals live on the shard owner, never re-indexed
      across shards (see IMPLEMENTING.md, "Why error feedback lives on
      the shard owner");
    * replicated GraceState fields (count/rng_key/fallback/audit) stay
      ``P()`` — bit-identical across BOTH axes, which is what lets the
      consensus audit fingerprint-match replicas *per fsdp shard* (its
      collectives run over ``dp_axis`` only).
    """

    dp_axis: str = DEFAULT_AXIS
    fsdp_axis: Optional[str] = None

    def __post_init__(self):
        if self.fsdp_axis is not None and self.fsdp_axis == self.dp_axis:
            raise ValueError(
                f"fsdp_axis must differ from dp_axis; both are "
                f"{self.dp_axis!r}")

    @property
    def axes(self) -> Tuple[str, ...]:
        """The mesh axis names, dp first."""
        if self.fsdp_axis is None:
            return (self.dp_axis,)
        return (self.dp_axis, self.fsdp_axis)

    @property
    def is_2d(self) -> bool:
        return self.fsdp_axis is not None

    def varying_spec(self):
        """PartitionSpec of a per-rank GraceState leaf's leading world
        axis: ``P(dp)`` on a 1-D mesh (bit-compatible with every
        pre-MeshSpec checkpoint/spec), ``P((dp, fsdp))`` on a 2-D mesh —
        one leading axis over the device *product*, one row per
        (dp, fsdp) rank."""
        from jax.sharding import PartitionSpec as P

        if self.fsdp_axis is None:
            return P(self.dp_axis)
        return P((self.dp_axis, self.fsdp_axis))

    @classmethod
    def normalize(cls, spec) -> "MeshSpec":
        """Accept the ergonomic spellings: an axis-name string (pure dp —
        every existing call site), a MeshSpec, or None (the default
        axis)."""
        if spec is None:
            return cls()
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, str):
            return cls(dp_axis=spec)
        raise TypeError(f"mesh must be an axis-name str or MeshSpec; got "
                        f"{type(spec).__name__}")


class AuditState(NamedTuple):
    """Replicated bookkeeping of the cross-rank consistency auditor.

    Threaded through ``GraceState.audit`` when ``grace_transform`` is built
    with ``consensus=...``; read and advanced in-graph by
    :func:`grace_tpu.resilience.consensus.consensus_step`. Every field is
    an int32 scalar, replicated across ranks (derived from all-gathered
    fingerprints, so all ranks compute identical values) — and is itself
    part of the audited/repaired replicated state.
    """

    audits: jax.Array                 # audits performed
    repairs: jax.Array                # repair events (any-rank divergence)
    escalations: jax.Array            # repeat-offender dense-fallback trips
    last_divergent_rank: jax.Array    # mesh index of last divergent rank, -1
    last_repair_step: jax.Array       # GraceState.count at last repair, -1


def audit_init() -> AuditState:
    zero = jnp.zeros((), jnp.int32)
    return AuditState(audits=zero, repairs=zero, escalations=zero,
                      last_divergent_rank=zero - 1, last_repair_step=zero - 1)


class GraceState(NamedTuple):
    count: jax.Array          # step counter (replicated)
    rng_key: jax.Array        # replicated base key, stored as raw key data
    mem: Tuple[State, ...]    # per-leaf memory state, leaf order of tree_flatten
    comp: Tuple[State, ...]   # per-leaf compressor state
    # Health flag (replicated): True routes the next update's exchange
    # through the dense escape hatch (see grace_transform(escape=...)).
    # Written by resilience.guard_transform via set_fallback_flag; plain
    # grace_transform never sets it, so the default False is a no-op.
    fallback: jax.Array = False
    # On-device telemetry ring (per-rank data, like mem/comp): a
    # grace_tpu.telemetry.TelemetryState when grace_transform was built with
    # telemetry=..., else None (an empty pytree node — invisible to
    # checkpointing, sharding, and the guard).
    telem: Any = None
    # Consensus-audit bookkeeping (replicated, like count/fallback): an
    # AuditState when grace_transform was built with consensus=..., else
    # None (an empty pytree node). grace_transform only *threads* it; the
    # audit itself runs at the train-step level (make_train_step(consensus=))
    # where params and the whole optimizer state are in scope — see
    # grace_tpu.resilience.consensus.
    audit: Any = None
    # graft-watch cross-rank health-summary ring (per-rank data, like
    # telem — the skew columns genuinely differ per rank): a
    # grace_tpu.telemetry.aggregate.WatchState when grace_transform was
    # built with watch=..., else None (an empty pytree node).
    watch: Any = None
    # graft-adapt in-graph controller state (replicated, like count/
    # fallback/audit — every field derives from the replicated step
    # counter, the replicated fallback flag, and full-axis pmean/pmax
    # outputs, so all ranks agree bitwise and the lax.switch rung
    # dispatch can never desync): a resilience.adapt.AdaptState when
    # grace_transform was built with adapt=..., else None.
    adapt: Any = None


# The GraceState field split every layout-aware consumer agrees on:
# VARYING fields hold genuinely per-rank data (leading world axis sharded
# over the mesh in the global view — partition_specs gives them P(axis));
# REPLICATED fields are bit-identical across ranks (P()) and are exactly
# what an elastic world-resize carries forward unchanged while the varying
# fields are re-initialized at the new world (see carry_replicated and
# grace_tpu.resilience.elastic — which deliberately RE-INITIALIZES the
# replicated `adapt` policy state at the new world: its windowed signal
# statistics and operating rung were learned at the old world's error
# profile).
GRACE_VARYING_FIELDS = ("mem", "comp", "telem", "watch")
GRACE_REPLICATED_FIELDS = ("count", "rng_key", "fallback", "audit",
                           "adapt")

# The OBSERVATIONAL subset of the varying fields: rings that record
# pipeline values verbatim (a poisoned gradient's norm, a cross-rank skew
# column) and therefore must never flip a guarded step bad on their own —
# the guard's check_state scan strips exactly these
# (resilience.guard._strip_telemetry ties its type-based strip to this
# list), while they still ROLL BACK with the rest of the inner state on a
# bad step. graft-sound's rollback-coverage pass reads this constant
# instead of re-deriving the contract from comments.
GRACE_OBSERVATIONAL_FIELDS = ("telem", "watch")


def _is_grace(x) -> bool:
    return isinstance(x, GraceState)


def _map_grace_varying(fn, tree):
    """Apply ``fn`` to the device-varying leaves (mem/comp/telem/watch) of
    every GraceState embedded in ``tree``; leave all other leaves
    untouched."""

    def per_node(node):
        if _is_grace(node):
            return node._replace(**{
                name: jax.tree_util.tree_map(fn, getattr(node, name))
                for name in GRACE_VARYING_FIELDS})
        return node

    return jax.tree_util.tree_map(per_node, tree, is_leaf=_is_grace)


def add_world_axis(tree):
    """Per-device → global layout: prepend a (local size 1) world axis to
    every mem/comp leaf. Call on values produced inside shard_map."""
    return _map_grace_varying(lambda x: x[None], tree)


def strip_world_axis(tree):
    """Global → per-device layout: drop this rank's world axis (local shards
    have leading dim 1 inside shard_map)."""

    def strip(x):
        if jnp.ndim(x) < 1 or x.shape[0] != 1:
            raise ValueError(
                "grace mem/comp state leaf has no leading world axis "
                f"(local shape {jnp.shape(x)}). Build training states with "
                "init_train_state/init_stateful_train_state(params, optimizer"
                ", mesh) — states built as optimizer.init(params) lack the "
                "sharded world axis and would be silently mis-sharded.")
        return x[0]

    return _map_grace_varying(strip, tree)


def partition_specs(tree, axis_name):
    """PartitionSpec pytree for a state pytree containing GraceState nodes.

    ``axis_name`` is an axis-name string (pure data parallelism — the
    historical signature) or a :class:`MeshSpec`. Per-rank GraceState
    leaves (mem/comp/telem/watch) shard their leading world axis over the
    mesh: ``P(dp)`` on a 1-D mesh, ``P((dp, fsdp))`` on a 2-D dp×fsdp
    mesh — per fsdp shard, the dp replicas' residuals/rings tile the same
    leading axis, so the global array holds one row per device and the
    shard owner keeps its own error feedback. Everything else (replicated
    GraceState fields and non-grace leaves) is ``P()``; params and
    param-shaped optimizer state on a sharded-model mesh carry their OWN
    fsdp specs, supplied by the caller (``make_train_step(param_specs=)``)
    — this function owns the GraceState contract, not the model's."""
    from jax.sharding import PartitionSpec as P

    mesh = MeshSpec.normalize(axis_name)
    vspec = mesh.varying_spec()

    def per_node(node):
        if _is_grace(node):
            return GraceState(
                count=jax.tree_util.tree_map(lambda _: P(), node.count),
                rng_key=jax.tree_util.tree_map(lambda _: P(), node.rng_key),
                mem=jax.tree_util.tree_map(lambda _: vspec, node.mem),
                comp=jax.tree_util.tree_map(lambda _: vspec, node.comp),
                fallback=jax.tree_util.tree_map(lambda _: P(),
                                                node.fallback),
                telem=jax.tree_util.tree_map(lambda _: vspec, node.telem),
                audit=jax.tree_util.tree_map(lambda _: P(), node.audit),
                watch=jax.tree_util.tree_map(lambda _: vspec, node.watch),
                adapt=jax.tree_util.tree_map(lambda _: P(), node.adapt))
        return jax.tree_util.tree_map(lambda _: P(), node)

    return jax.tree_util.tree_map(per_node, tree, is_leaf=_is_grace)


def set_fallback_flag(tree, active) -> Any:
    """Write ``active`` into the ``fallback`` flag of every GraceState in
    ``tree``. Used by :func:`grace_tpu.resilience.guard_transform` to route
    the next step's exchange through the dense escape hatch; a no-op on
    trees without GraceState nodes."""
    active = jnp.asarray(active, jnp.bool_)

    def per_node(node):
        if _is_grace(node):
            return node._replace(fallback=active)
        return node

    return jax.tree_util.tree_map(per_node, tree, is_leaf=_is_grace)


def fallback_flags(tree) -> list:
    """The ``fallback`` flags of every GraceState in ``tree`` (leaf order)."""
    flags = []

    def per_node(node):
        if _is_grace(node):
            flags.append(node.fallback)
        return node

    jax.tree_util.tree_map(per_node, tree, is_leaf=_is_grace)
    return flags


def carry_replicated(old_tree, fresh_tree, convert=None):
    """Graft the replicated payload of ``old_tree`` onto ``fresh_tree``.

    The transform-level re-shard hook of elastic training
    (:mod:`grace_tpu.resilience.elastic`): ``fresh_tree`` is a
    freshly-initialized state pytree (same structure, per-rank leaves
    sized for the NEW world), ``old_tree`` the pre-resize state. Every
    GraceState keeps the fresh :data:`GRACE_VARYING_FIELDS`
    (mem/comp/telem/watch — re-initialized, never re-partitioned; see
    IMPLEMENTING.md, "Why re-shard re-initializes residuals") and takes
    the old :data:`GRACE_REPLICATED_FIELDS` (count/rng_key/fallback/audit)
    bit-exactly; every non-GraceState leaf (params-adjacent optimizer
    state, guard counters) is carried from ``old_tree`` — those are
    replicated by the ``partition_specs`` contract. ``convert`` (e.g. a
    ``device_put`` onto the new mesh) is applied to each carried leaf.
    ``old_tree`` may hold ``None`` in the varying fields (a stripped
    :func:`~grace_tpu.resilience.consensus.replicated_view`) — only its
    replicated payload is read."""
    conv = convert if convert is not None else (lambda x: x)

    def graft(old, fresh):
        if _is_grace(old):
            if not _is_grace(fresh):
                raise ValueError(
                    "carry_replicated: old tree has a GraceState where the "
                    f"fresh tree has {type(fresh).__name__} — the two "
                    "states were built from different optimizer chains.")
            return fresh._replace(**{
                name: jax.tree_util.tree_map(conv, getattr(old, name))
                for name in GRACE_REPLICATED_FIELDS})
        return conv(old)

    return jax.tree_util.tree_map(graft, old_tree, fresh_tree,
                                  is_leaf=_is_grace)


def _migrate_leaf(old, fresh):
    """One leaf of the cross-config state migration map. Returns
    ``(leaf, verdict)``:

    * ``carried`` — same shape+dtype: the old leaf moves bit-exactly
      (a PowerSGD Q whose padded layout did not change, a residual whose
      gradient-space shape is config-independent).
    * ``overlap`` — same dtype and same dims except the LAST axis: the
      shared leading columns carry (``min(k_old, k_new)``), the rest keep
      the fresh init. This is the PowerSGD rank-change rule: Q columns
      are per-direction power-iteration state, so the directions both
      layouts track warm-start and only genuinely new columns start from
      the fresh draw.
    * ``fresh`` — anything else (different codec family, different
      matricization): no meaningful warm state exists; zero/fresh-init is
      the PR-3 rationale's demand.
    """
    if old is None or fresh is None:
        return fresh, "carried" if (old is None and fresh is None) else "fresh"
    if not (hasattr(old, "shape") and hasattr(fresh, "shape")):
        return fresh, "fresh"
    if old.dtype != fresh.dtype:
        return fresh, "fresh"
    if old.shape == fresh.shape:
        return old, "carried"
    if (old.ndim == fresh.ndim and old.ndim >= 1
            and old.shape[:-1] == fresh.shape[:-1]):
        k = min(old.shape[-1], fresh.shape[-1])
        return fresh.at[..., :k].set(old[..., :k]), "overlap"
    return fresh, "fresh"


def migrate_state_tree(old, fresh):
    """Leafwise migration of one varying-state pytree (a GraceState
    ``mem`` or ``comp`` field) from an OLD config's layout onto a FRESH
    init under the new config. Structures that do not match at the pytree
    level migrate nothing (the new codec family keeps its fresh state).
    Returns ``(tree, {"carried": n, "overlap": n, "fresh": n,
    "structure_match": bool})``."""
    old_td = jax.tree_util.tree_structure(old)
    fresh_td = jax.tree_util.tree_structure(fresh)
    stats = {"carried": 0, "overlap": 0, "fresh": 0,
             "structure_match": old_td == fresh_td}
    if not stats["structure_match"]:
        stats["fresh"] = len(jax.tree_util.tree_leaves(fresh))
        return fresh, stats

    def leaf(o, f):
        out, verdict = _migrate_leaf(o, f)
        stats[verdict] += 1
        return out

    return jax.tree_util.tree_map(leaf, old, fresh), stats


def migrate_grace_state(old_tree, fresh_tree, convert=None):
    """Cross-CONFIG GraceState migration — the retune promotion's state
    surgery, same shape as :func:`carry_replicated` (elastic's
    cross-WORLD hook) but at a fixed world with a possibly different
    codec/ladder:

    * replicated fields ``count``/``rng_key``/``fallback``/``audit``
      carry bit-exactly (step counter and consensus history continue
      across the cutover);
    * ``adapt`` takes the FRESH policy state — the ladder changed, so
      the windowed statistics and operating rung learned under the old
      config are meaningless (the elastic ``_reinit_adapt`` rationale);
    * ``mem``/``comp`` migrate leafwise through :func:`migrate_state_tree`
      — error-feedback residuals are gradient-shaped and codec-agnostic
      (carried when shapes agree), compressor state carries whole or by
      column overlap (PowerSGD warm start across promotions), else fresh;
    * ``telem``/``watch`` take the fresh rings — per-rung wire plans and
      window statistics are priced against the NEW config; splicing old
      rows under new pricing would fabricate evidence;
    * non-GraceState leaves (optimizer moments, guard counters) carry
      from ``old_tree`` — replicated by the ``partition_specs`` contract.

    Returns ``(state, stats)`` with per-field migration counts for the
    PREPARE audit record.
    """
    conv = convert if convert is not None else (lambda x: x)
    stats = {"mem": {"carried": 0, "overlap": 0, "fresh": 0},
             "comp": {"carried": 0, "overlap": 0, "fresh": 0},
             "mem_structure_match": True, "comp_structure_match": True}

    def graft(old, fresh):
        if _is_grace(old):
            if not _is_grace(fresh):
                raise ValueError(
                    "migrate_grace_state: old tree has a GraceState where "
                    f"the fresh tree has {type(fresh).__name__} — the two "
                    "states were built from different optimizer chains.")
            mem, ms = migrate_state_tree(old.mem, fresh.mem)
            comp, cs = migrate_state_tree(old.comp, fresh.comp)
            for k in ("carried", "overlap", "fresh"):
                stats["mem"][k] += ms[k]
                stats["comp"][k] += cs[k]
            stats["mem_structure_match"] &= ms["structure_match"]
            stats["comp_structure_match"] &= cs["structure_match"]
            rep = {name: jax.tree_util.tree_map(conv, getattr(old, name))
                   for name in GRACE_REPLICATED_FIELDS if name != "adapt"}
            return fresh._replace(mem=jax.tree_util.tree_map(conv, mem),
                                  comp=jax.tree_util.tree_map(conv, comp),
                                  **rep)
        return conv(old)

    out = jax.tree_util.tree_map(graft, old_tree, fresh_tree,
                                 is_leaf=_is_grace)
    return out, stats


def leaf_path_str(path) -> str:
    """The ``"/"``-joined spelling of a ``tree_flatten_with_path`` key path
    — the string codec routes match against (and the same spelling the
    static auditor's state paths use)."""
    parts = []
    for e in path:
        for attr in ("name", "key", "idx"):
            if hasattr(e, attr):
                parts.append(str(getattr(e, attr)))
                break
        else:
            parts.append(str(e))
    return "/".join(parts)


def normalize_routes(routes, base_communicator) -> Tuple:
    """Normalize a per-leaf codec routing table to
    ``((pattern, compressor, memory, communicator), ...)``.

    Each entry is ``(pattern, triad)`` where ``pattern`` is an
    ``fnmatch`` glob matched against the leaf's ``"/"``-joined tree path
    (``"*emb*"``, ``"layers/*/ln*/*"``) and ``triad`` is either a
    3-tuple ``(compressor, memory, communicator)`` or any object with
    those attributes (a :class:`grace_tpu.helper.Grace` bundle). First
    match wins; unmatched leaves ride the transform's base triad. Every
    route's communicator must exchange over the SAME mesh axis as the
    base one — per-leaf pipelines issue separate collectives, but they
    all rendezvous on one dp axis."""
    out = []
    for entry in routes:
        if len(entry) == 4:          # already-normalized 4-tuple
            pat, comp, mem, cm = entry
        else:
            pat, triad = entry
            if isinstance(triad, (tuple, list)):
                if len(triad) != 3:
                    raise ValueError(
                        f"route {pat!r}: triad must be (compressor, "
                        f"memory, communicator); got {len(triad)} "
                        "elements")
                comp, mem, cm = triad
            else:
                comp, mem, cm = (triad.compressor, triad.memory,
                                 triad.communicator)
        if cm.axis_name != base_communicator.axis_name:
            raise ValueError(
                f"route {pat!r}: communicator axis {cm.axis_name!r} "
                f"differs from the base communicator's "
                f"{base_communicator.axis_name!r} — all routed exchanges "
                "must rendezvous on one dp axis")
        out.append((str(pat), comp, mem, cm))
    return tuple(out)


def route_for(routes, path_str: str, default):
    """The ``(compressor, memory, communicator)`` triad for one leaf path:
    the first route whose pattern matches, else ``default``."""
    for pat, comp, mem, cm in routes:
        if fnmatch.fnmatchcase(path_str, pat):
            return comp, mem, cm
    return default


def _bucketize(shapes_dtypes, bucket_bytes: Optional[int]):
    """Group leaf indices into fusion buckets of at most ``bucket_bytes``
    (whole leaves only; an oversized leaf gets its own bucket). ``None``
    means one bucket for everything. Deterministic in leaf order, so init
    and update always agree — and bucket count/ordering is a pinned
    contract (tests/test_fusion.py): the static auditor's schedulability
    pass derives the promised number of independent compress→exchange
    chains from this exact plan. Concatenating the buckets always yields
    ``range(n)``; an empty leaf list yields NO buckets (not one empty
    bucket — an empty bucket would make the fused update concatenate
    nothing). Returns (buckets, common_dtype)."""
    n = len(shapes_dtypes)
    cdtype = jnp.result_type(*(d for _, d in shapes_dtypes)) \
        if shapes_dtypes else jnp.float32
    if bucket_bytes is None:
        return ([list(range(n))] if n else []), cdtype
    itemsize = jnp.dtype(cdtype).itemsize
    buckets, cur, cur_bytes = [], [], 0
    for i, (shape, _) in enumerate(shapes_dtypes):
        nbytes = int(np.prod(shape, dtype=np.int64)) * itemsize
        if cur and cur_bytes + nbytes > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets, cdtype


def _group_views(leaves):
    """Grouped-fusion plan: leaf-index lists keyed by (shape, dtype), in
    first-appearance order. Deterministic in leaf order so init and update
    always agree on group numbering."""
    groups: dict = {}
    for i, leaf in enumerate(leaves):
        key = (jnp.shape(leaf), str(jnp.result_type(leaf)))
        groups.setdefault(key, []).append(i)
    return list(groups.values())


def fusion_payload_structs(leaves, fusion) -> list:
    """``[(struct, multiplicity), ...]`` — the exact tensor structures the
    active fusion mode hands the codec, one entry per distinct compress
    call shape. Per-leaf: every leaf, ×1. ``'grouped'``: one representative
    per shape group, ×group size (vmap batches identical compressions).
    ``'flat'``/int buckets: one flat common-dtype buffer per bucket, ×1 —
    for int buckets this is also the executor's chain plan: one entry ==
    one independent compensate→compress→exchange pipeline. Shared by the
    wire models here, the static auditor's payload-contract checks
    (:mod:`grace_tpu.analysis.flow`), and the per-bucket telemetry pricing,
    so they can never enumerate different structures."""
    structs = [jax.ShapeDtypeStruct(tuple(jnp.shape(l)), jnp.result_type(l))
               for l in leaves]
    if fusion == "grouped":
        return [(structs[idxs[0]], len(idxs))
                for idxs in _group_views(structs)]
    if fusion is None:
        return [(s, 1) for s in structs]
    bucket_bytes = None if fusion == "flat" else int(fusion)
    buckets, cdtype = _bucketize(
        [(s.shape, s.dtype) for s in structs], bucket_bytes)
    return [(jax.ShapeDtypeStruct(
        (sum(int(np.prod(structs[i].shape, dtype=np.int64))
             for i in idxs),), jnp.dtype(cdtype)), 1)
        for idxs in buckets]


def fusion_payload_nbytes(compressor: Compressor, leaves, fusion
                          ) -> Tuple[int, int, int]:
    """``(dense_bytes, payload_bytes, n_elems)`` for these gradient leaves
    under a fusion setting (None | 'flat' | 'grouped' | int bucket bytes).

    ``dense_bytes`` is the raw dense gradient size (the codec-blind
    reference), ``payload_bytes`` one rank's whole-gradient wire payload
    priced over the exact structures the fusion mode compresses
    (:func:`fusion_payload_structs`), ``n_elems`` the dense element count.
    Module-level so the telemetry wire plan inside :func:`grace_transform`
    and the static auditor's wire-byte reconciliation pass
    (:mod:`grace_tpu.analysis`) price payloads with literally the same code
    — drift between the priced model and the traced graph is then a lint
    finding, never a silent disagreement.
    """
    from grace_tpu.utils.metrics import payload_nbytes

    structs = [jax.ShapeDtypeStruct(tuple(jnp.shape(l)), jnp.result_type(l))
               for l in leaves]
    n_elems = sum(int(np.prod(s.shape, dtype=np.int64)) for s in structs)
    dense = sum(int(np.prod(s.shape, dtype=np.int64)) * s.dtype.itemsize
                for s in structs)
    comp_b = sum(payload_nbytes(compressor, s) * count
                 for s, count in fusion_payload_structs(structs, fusion))
    return dense, comp_b, n_elems


def _normalize_telemetry(telemetry) -> Optional[TelemetryConfig]:
    """Accept the ergonomic spellings of the telemetry knob: None/False
    (off), True (defaults), int (ring capacity), dict (config kwargs), or a
    TelemetryConfig."""
    if telemetry is None or telemetry is False:
        return None
    if telemetry is True:
        return TelemetryConfig()
    if isinstance(telemetry, TelemetryConfig):
        return telemetry
    if isinstance(telemetry, int):
        return TelemetryConfig(capacity=telemetry)
    if isinstance(telemetry, dict):
        return TelemetryConfig(**telemetry)
    raise TypeError(f"telemetry must be None/bool/int/dict/TelemetryConfig; "
                    f"got {type(telemetry).__name__}")


def grace_transform(compressor: Compressor, memory: Memory,
                    communicator: Communicator, seed: int = 0,
                    fusion: Optional[int | str] = None,
                    escape: Optional[Compressor] = None,
                    telemetry=None,
                    consensus=None,
                    topology: Optional[Topology] = None,
                    watch=None,
                    mesh=None,
                    routes: Optional[Sequence] = None,
                    adapt=None
                    ) -> optax.GradientTransformation:
    """Build the compressed-exchange transformation.

    The returned transform maps *local* (per-device) gradients to globally
    aggregated ones, exactly like ``Communicator.step`` in the reference
    (grace_dl/dist/__init__.py:47-52) but over whole pytrees.

    ``fusion`` is the TPU-native analog of Horovod's C++ fusion buffer
    (SURVEY.md §2.4: the reference inherits tensor fusion from Horovod's
    background coordinator; the dist backend has none and pays one NCCL call
    per tensor, SURVEY.md §3.3). Options:

    * ``None`` — per-leaf pipeline: one compress+collective per parameter,
      matching the reference's per-tensor semantics exactly (Top-K ratio
      applied per tensor, etc.).
    * ``'flat'`` — concatenate every gradient into ONE flat buffer: one
      compress + one collective for the whole model. Fewer, larger
      collectives ride ICI far better; selection-based compressors then pick
      k over the whole model (cross-tensor Top-K — slightly different but
      generally *stronger* selection than per-tensor).
    * ``'grouped'`` — stack same-(shape, dtype) leaves and ``jax.vmap`` the
      whole per-leaf pipeline over each stack: G same-shaped tensors cost
      one *batched* compress (e.g. PowerSGD's G small QRs/matmuls become
      batched MXU ops) and one batched collective instead of G small ones,
      while per-tensor semantics are preserved EXACTLY (vmap is just
      batching — unlike ``'flat'``, which changes selection semantics;
      grouped-vs-per-leaf bit-equality is pinned in tests/test_fusion.py).
      Measured single-chip (BERT-base + PowerSGD r4, TPU v5e 2026-08-01):
      **0.90× of per-leaf** — under XLA there is no per-op dispatch cost
      to amortize (everything is one compiled program either way), so the
      stack/unstack HBM copies are pure overhead on one chip. The case
      for 'grouped' is multi-chip: one batched psum replaces G per-leaf
      collectives, cutting per-collective latency on real meshes — weigh
      it against the measured single-chip cost on your topology. Per-leaf
      RNG derivation differs from ``None`` mode (keys split per group,
      not folded per leaf index), so stochastic codecs draw different —
      equally valid — randomness.
    * ``int`` — greedy whole-leaf buckets of at most this many bytes
      (Horovod's default fusion threshold is 64 MiB), executed as the
      **bucketed overlap executor**: K data-independent pipelines, each
      running its bucket's full compensate→compress→exchange→decompress→
      memory-update chain under its own rng and its own
      ``grace/bucket/<b>`` trace scope. Bucket b's collective depends only
      on bucket b's gradient leaves, so XLA's latency-hiding scheduler can
      overlap bucket i's exchange with bucket i+1's compression and the
      tail of the backward pass (DDP-style bucket scheduling) — the
      contract graft-flow's ``overlap_schedulability`` pass enforces (K
      independent compress→exchange chains in the traced graph) and
      graft-prof's measured overlap fraction is sandwiched against.
      Resilience and accounting stay step-atomic across the split: the
      guard checks once after ALL buckets land and rolls back the whole
      step (per-bucket rollback would desync error feedback between
      buckets), the consensus audit fingerprints the post-apply state as
      one unit, and the telemetry row sums the per-bucket wire prices
      (each bucket's collective priced separately through
      ``recv_link_bytes``) into one step row.

    Leaves are cast to their common result dtype inside a fused buffer and
    cast back on return.

    ``escape`` (resilience escape hatch, no reference analog): a dense-safe
    compressor (``NoneCompressor``/``FP16Compressor``) that, whenever the
    state's ``fallback`` flag is set, replaces the whole compressed pipeline
    for one step with ``escape``-encode → psum → decode over the same mesh
    axis (classic dense all-reduce semantics) via `lax.cond` — mem/comp
    state is left untouched, so compression resumes exactly where it left
    off when the flag clears. The flag is driven by
    :func:`grace_tpu.resilience.guard_transform`; without a guard it stays
    False and the cond always takes the compressed branch.

    ``telemetry`` (None | True | int capacity | dict | ``TelemetryConfig``):
    arm the in-graph telemetry ring (:mod:`grace_tpu.telemetry`). Every
    update then records per-step scalars — gradient/update norms,
    residual-memory norm and max (error-feedback health), the relative
    compression error ``‖g − decompress(compress(g))‖/‖g‖``, and the
    *effective* wire bytes — COMMUNICATOR-AWARE bytes received per rank per
    step (``Communicator.recv_wire_bytes``: allgather pays (W−1)·payload,
    ring/two-shot ≈2·payload·(W−1)/W), which flip to the ``escape`` codec's
    dense psum cost while the fallback flag is set — into a bounded
    on-device ring buffer
    (``GraceState.telem``) with zero host syncs; drain it with
    :class:`grace_tpu.telemetry.TelemetryReader`. The compression-error
    metric re-runs compress→decompress on the step's gradients (XLA CSEs
    the duplicate when no error-feedback memory rewrites the input); set
    ``TelemetryConfig(compression_error=False)`` to make telemetry
    near-free.

    ``topology`` (None | :class:`grace_tpu.core.Topology`): the mesh link
    layout the telemetry ring prices its per-link wire split with — every
    row's ``wire_bytes_ici``/``wire_bytes_dcn`` come from
    ``Communicator.recv_link_bytes`` under this topology (flat
    communicators therefore report the all-ICI split within one slice and
    all-DCN beyond it; the hierarchical communicator reports a genuinely
    mixed split). ``None`` auto-detects the live layout ONCE, at build
    time (``Topology.detect()`` — a single slice on CPU/simulated meshes,
    which is the documented all-ICI fallback for flat comms); every wire
    consumer inside the transform then shares that single resolved object,
    so an elastic world resize invalidates the topology by rebuilding the
    transform and nowhere else.

    ``consensus`` (None | True | int ``audit_every`` | dict |
    ``ConsensusConfig``): arm the cross-rank consistency auditor
    (:mod:`grace_tpu.resilience.consensus`) by threading an
    :class:`AuditState` through ``GraceState.audit``. The transform only
    carries the state — the audit hook itself runs at the train-step level
    (``make_train_step(consensus=...)``), where params and the full
    optimizer state are in scope for fingerprinting and repair. Any truthy
    value arms the state; the schedule/repair knobs are read from the
    config handed to the train step.

    ``mesh`` (None | axis-name str | :class:`MeshSpec`): the mesh layout
    the transform runs under. ``None``/str is pure data parallelism over
    the communicator's axis (today's behavior, unchanged byte-for-byte).
    A 2-D :class:`MeshSpec` declares the sharded-model track: the
    communicator's ``axis_name`` must equal ``mesh.dp_axis`` (the
    exchange is the per-shard reduce over dp; a collective over the dp
    axis inside a 2-D shard_map operates within each fsdp shard's dp
    group automatically), and ``partition_specs`` built from the same
    MeshSpec shards the per-rank GraceState leaves over the dp×fsdp
    product — residuals live on the shard owner.

    ``routes`` (None | ``[(pattern, triad), ...]``): first-class per-leaf
    codec routing (see :func:`normalize_routes`). Wire bytes in a
    transformer concentrate in embeddings/tied layers while
    LayerNorm/bias leaves hate sparsification — routing gives each leaf
    family its own (compressor, memory, communicator) triad, matched by
    fnmatch glob against the leaf's tree path, with unmatched leaves on
    the base triad. Requires ``fusion=None``: routing IS per-leaf
    semantics (a flat/bucketed concat would fuse leaves with different
    codecs into one payload). The telemetry wire plan, the per-link
    split, and the static auditor's wire reconciliation all price routed
    configs as the SUM of per-leaf prices through each leaf's own codec
    and communicator.

    ``watch`` (None | True | int ``window`` | dict | ``WatchConfig``): arm
    graft-watch (:mod:`grace_tpu.telemetry.aggregate`) — every
    ``window``-th step all_gathers each rank's local health vector
    (grad norm, compression error, residual norm) and writes a replicated
    cross-rank mean/min/max summary plus the per-rank **skew** (deviation
    from the replicated mean) into a bounded on-device ring
    (``GraceState.watch``), gated by a ``lax.cond`` on the replicated step
    counter exactly like the consensus audit. Costs one tiny collective
    per window (``(W-1)·12`` B received per rank), folded honestly into
    the telemetry row's ``wire_bytes``/``wire_bytes_ici``/
    ``wire_bytes_dcn`` and surfaced as ``watch_bytes``. Requires
    ``telemetry=...`` — the health scalars are the telemetry row's, and
    without a ring there is nowhere to account the gather's wire cost.

    ``adapt`` (None | True | int ``window`` | dict |
    :class:`grace_tpu.resilience.adapt.AdaptConfig`): arm the in-graph
    adaptive compression controller (graft-adapt). The declared
    **degradation ladder** replaces the single static codec: rung 0 is
    the dense escape (requires ``escape=...`` — rung 0 IS the escape
    path), rungs 1..R-1 the config's ladder codecs (safest first), and
    the transform's base ``compressor`` is always the top rung — the
    steady state a quiet run converges to. Every update executes exactly
    one rung via ``lax.switch`` on the replicated rung index (the
    guard's fallback flag forces rung 0, so the M-step dense window is
    the same branch), and every ``window`` steps the controller moves
    the rung from the replicated windowed compression-error signal (one
    scalar pmean + pmax per step — see
    :mod:`grace_tpu.resilience.adapt` for the tighten/loosen/
    escalate-and-hold semantics). Requires ``telemetry=...`` with
    ``compression_error=True`` (the signal IS the telemetry row's
    relative compression error, computed against the active rung's
    codec) and ``routes=None`` (the ladder swaps the base codec
    wholesale; per-leaf route sub-triads are outside the rung plan).
    Telemetry prices each row at the ACTIVE rung via a per-rung wire
    plan — the dense-fallback byte flip generalized to R rungs — and
    surfaces the rung as ``adapt_rung`` plus the signal reductions' cost
    as ``adapt_bytes``. Policy state (``GraceState.adapt``) is
    replicated: fingerprinted by the consensus audit, repaired by the
    masked broadcast, rolled back bitwise by the guard, re-initialized
    by an elastic world resize.
    """
    telemetry = _normalize_telemetry(telemetry)
    watch = normalize_watch(watch)
    if adapt is not None and adapt is not False:
        # Lazy import: resilience.__init__ imports guard, which imports
        # this module — a module-level import here would cycle.
        from grace_tpu.resilience.adapt import normalize_adapt
        adapt = normalize_adapt(adapt, compressor)
    else:
        adapt = None
    mesh = MeshSpec.normalize(mesh if mesh is not None
                              else communicator.axis_name)
    if mesh.dp_axis != communicator.axis_name:
        raise ValueError(
            f"mesh.dp_axis {mesh.dp_axis!r} differs from the "
            f"communicator's axis_name {communicator.axis_name!r} — the "
            "compressed exchange IS the per-shard reduce over the dp "
            "axis, so the two must name the same mesh axis.")
    routes = (normalize_routes(routes, communicator) if routes else ())
    if routes and fusion is not None:
        raise ValueError(
            "routes=... requires fusion=None: per-leaf codec routing is "
            "per-leaf semantics — 'flat'/'grouped'/bucketed fusion "
            "concatenates or stacks leaves, which would fuse leaves "
            "with different codecs into one payload. Route instead of "
            "fusing (each leaf family already gets its own collective).")
    if watch is not None and telemetry is None:
        raise ValueError(
            "watch=... requires telemetry=...: graft-watch summarizes the "
            "telemetry row's health scalars cross-rank and folds its "
            "gather cost into the ring's wire_bytes — arm "
            "grace_transform(telemetry=True) (or a capacity/config) "
            "alongside watch.")
    if adapt is not None:
        if escape is None:
            raise ValueError(
                "adapt=... requires escape=...: the degradation ladder's "
                "rung 0 IS the dense escape path (the same codec+psum the "
                "guard's fallback window routes through) — arm "
                "grace_transform(escape=FP16Compressor()/NoneCompressor()) "
                "alongside adapt.")
        if telemetry is None or not telemetry.compression_error:
            raise ValueError(
                "adapt=... requires telemetry=... with "
                "compression_error=True: the controller's windowed signal "
                "IS the telemetry row's relative compression error "
                "(computed against the active rung's codec) — arm "
                "grace_transform(telemetry=True) alongside adapt.")
        if routes:
            raise ValueError(
                "adapt=... requires routes=None: the ladder swaps the "
                "base codec wholesale each rung; per-leaf route "
                "sub-triads are outside the rung plan (route OR adapt, "
                "not both).")
    consensus_armed = consensus is not None and consensus is not False
    if escape is not None and not (getattr(escape, "summable_payload", False)
                                   and escape.average):
        raise ValueError(
            "escape must be a dense, summable, averaging compressor "
            "(NoneCompressor/FP16Compressor) — the escape hatch psums its "
            f"payload; got {type(escape).__name__}.")
    if isinstance(fusion, str) and fusion not in ("flat", "grouped"):
        raise ValueError(f"fusion must be None, 'flat', 'grouped', or int "
                         f"bytes; got {fusion!r}")
    grouped = fusion == "grouped"
    if grouped and getattr(communicator, "shard_parallel", False):
        raise ValueError(
            "fusion='grouped' vmaps the per-leaf pipeline over leaf stacks "
            "and is validated for the exchange-based communicator families "
            "(Allreduce/Allgather/Broadcast/SignAllreduce/Identity); "
            f"{type(communicator).__name__} re-chunks the gradient into "
            "per-rank shards inside step() (shard-parallel family: "
            "TwoShotAllreduce/RingAllreduce/HierarchicalAllreduce), and "
            "vmapping its all_to_all/ppermute schedule is not a traced "
            "path — use "
            "fusion=None, 'flat', or integer byte buckets, which hand the "
            "communicator whole buffers to shard.")
    bucket_bytes = None if fusion == "flat" else fusion
    fused = fusion is not None and not grouped
    # Resolve the link topology ONCE, at build time. Both consumers below
    # (the wire-plan pricing and the watch-gather link fold) close over this
    # single object, so they can never disagree — and an elastic world
    # resize has exactly one invalidation point: rebuild the transform
    # (which a resize must do anyway to re-size the per-rank state).
    # Detection is only needed when telemetry prices a per-link split.
    resolved_topology = topology
    if resolved_topology is None and telemetry is not None:
        resolved_topology = Topology.detect()

    def _bucket_views(leaves):
        """Static bucketing plan for these leaves: (buckets, common dtype)."""
        return _bucketize([(jnp.shape(l), jnp.result_type(l))
                           for l in leaves], bucket_bytes)

    _base_triad = (compressor, memory, communicator)

    def _leaf_triads(tree):
        """Per-leaf (compressor, memory, communicator) plan for a pytree:
        (paths, triads), first matching route wins, base triad otherwise.
        Deterministic in leaf order so init and update always agree."""
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        paths = [leaf_path_str(p) for p, _leaf in flat]
        return paths, [route_for(routes, p, _base_triad) for p in paths]

    def init(params) -> GraceState:
        leaves = jax.tree_util.tree_leaves(params)
        if routes:
            _, triads = _leaf_triads(params)
            mem = tuple(m.init_state(p)
                        for p, (_c, m, _cm) in zip(leaves, triads))
            comp = tuple(c.init_state(p)
                         for p, (c, _m, _cm) in zip(leaves, triads))
            return GraceState(
                count=jnp.zeros((), jnp.int32),
                rng_key=jax.random.key_data(jax.random.key(seed)),
                mem=mem, comp=comp,
                fallback=jnp.zeros((), jnp.bool_),
                telem=(telemetry_init(telemetry)
                       if telemetry is not None else None),
                audit=audit_init() if consensus_armed else None,
                watch=(watch_init(watch) if watch is not None else None),
                adapt=None)
        if grouped:
            stacks = [jnp.stack([leaves[i] for i in idxs])
                      for idxs in _group_views(leaves)]
            mem = tuple(jax.vmap(memory.init_state)(s) for s in stacks)
            comp = tuple(jax.vmap(compressor.init_state)(s) for s in stacks)
        elif fused:
            buckets, cdtype = _bucket_views(leaves)
            flats = [jnp.concatenate([jnp.ravel(leaves[i]).astype(cdtype)
                                      for i in idxs]) for idxs in buckets]
            mem = tuple(memory.init_state(f) for f in flats)
            comp = tuple(compressor.init_state(f) for f in flats)
        else:
            mem = tuple(memory.init_state(p) for p in leaves)
            comp = tuple(compressor.init_state(p) for p in leaves)
        # Raw key data (uint32) instead of a typed key array so the whole
        # state is plain-array checkpointable with any writer.
        adapt_state = None
        if adapt is not None:
            from grace_tpu.resilience.adapt import adapt_init
            adapt_state = adapt_init(adapt)
        return GraceState(count=jnp.zeros((), jnp.int32),
                          rng_key=jax.random.key_data(jax.random.key(seed)),
                          mem=mem, comp=comp,
                          fallback=jnp.zeros((), jnp.bool_),
                          telem=(telemetry_init(telemetry)
                                 if telemetry is not None else None),
                          audit=audit_init() if consensus_armed else None,
                          watch=(watch_init(watch)
                                 if watch is not None else None),
                          adapt=adapt_state)

    def _run_compressed(operand, codec: Optional[Compressor] = None):
        # ``codec`` overrides the base compressor for one call — the
        # graft-adapt ladder dispatch runs this same executor once per
        # rung branch with the rung's codec; everything else (memory,
        # communicator, fusion plan, rng derivation) is rung-invariant,
        # which is what keeps the lax.switch branches structurally
        # interchangeable.
        compressor_ = codec if codec is not None else compressor
        leaves, mem, comp, step_key = operand
        new_mem, new_comp = [], []
        if grouped:
            groups = _group_views(leaves)
            if len(mem) != len(groups):
                raise ValueError(
                    f"grace state has {len(mem)} groups but the "
                    f"leaves form {len(groups)} — the state was built under "
                    "a different fusion setting. Re-init the optimizer "
                    "state (or restore a checkpoint written with the same "
                    "fusion config).")
            outs = [None] * len(leaves)
            for gi, idxs in enumerate(groups):
                # Group COUNT can coincide between fusion settings (e.g. a
                # per-leaf state whose leaves all have distinct shapes);
                # the stacked leading dim cannot — validate it here so a
                # stale state raises the re-init message instead of an
                # opaque vmap batch-dimension error.
                for leaf in jax.tree_util.tree_leaves((mem[gi], comp[gi])):
                    if hasattr(leaf, "shape") and (
                            jnp.ndim(leaf) < 1
                            or leaf.shape[0] != len(idxs)):
                        raise ValueError(
                            f"grace state group {gi} has a leaf of shape "
                            f"{jnp.shape(leaf)} but the group stacks "
                            f"{len(idxs)} same-shaped leaves (expected "
                            f"leading dim {len(idxs)}) — the state was "
                            "built under a different fusion setting. "
                            "Re-init the optimizer state (or restore a "
                            "checkpoint written with the same fusion "
                            "config).")
                stacked = jnp.stack([leaves[i] for i in idxs])
                keys = jax.random.split(
                    jax.random.fold_in(step_key, gi), len(idxs))

                def one(g, ms, cs, key):
                    return communicator.step(g, ms, cs, memory, compressor_,
                                             key)

                out, ms, cs = jax.vmap(one)(stacked, mem[gi],
                                            comp[gi], keys)
                for j, i in enumerate(idxs):
                    outs[i] = out[j]
                new_mem.append(ms)
                new_comp.append(cs)
        elif fused:
            # Bucketed overlap executor: K data-independent pipelines, one
            # per fusion bucket. Each bucket's FULL chain — concatenate its
            # own leaves, compensate against its own residual buffer,
            # compress, exchange, decompress, update its own memory — runs
            # under a per-bucket rng (fold_in(step_key, b)) and touches no
            # other bucket's values, so bucket b's collective depends only
            # on bucket b's gradient leaves. That dataflow independence is
            # the whole point: XLA's latency-hiding scheduler may then run
            # bucket i's exchange under bucket i+1's compression and under
            # whatever tail of the backward pass produces later buckets'
            # gradients (DDP-style bucket scheduling). The contract is
            # ENFORCED, not hoped for: graft-flow's overlap_schedulability
            # pass counts the independent compress→exchange chains in the
            # traced graph and fails lint when fewer than len(buckets)
            # survive — any accidental cross-bucket dependency introduced
            # here is a CI error, not a silent serialization. Per-bucket
            # "grace/bucket/<b>" scopes make each chain attributable in a
            # device trace (the measured side of the overlap sandwich);
            # 'flat' is the K=1 degenerate case of the same executor.
            buckets, cdtype = _bucket_views(leaves)
            if len(mem) != len(buckets):
                raise ValueError(
                    f"grace state has {len(mem)} buffers but the "
                    f"fusion plan has {len(buckets)} buckets — the state was "
                    "built under a different fusion setting. Re-init the "
                    "optimizer state (or restore a checkpoint written with "
                    "the same fusion config).")
            outs = [None] * len(leaves)
            for b, idxs in enumerate(buckets):
                with trace_stage(f"{STAGE_BUCKET}/{b}"):
                    rng = jax.random.fold_in(step_key, b)
                    flat = jnp.concatenate([jnp.ravel(leaves[i]).astype(
                        cdtype) for i in idxs])
                    out, ms, cs = communicator.step(
                        flat, mem[b], comp[b], memory, compressor_, rng)
                    off = 0
                    for i in idxs:
                        shape = jnp.shape(leaves[i])
                        size = int(np.prod(shape, dtype=np.int64)) \
                            if shape else 1
                        piece = out[off:off + size]
                        outs[i] = piece.reshape(shape).astype(
                            jnp.result_type(leaves[i]))
                        off += size
                new_mem.append(ms)
                new_comp.append(cs)
        else:
            outs = []
            triads = _route_plan[0] if routes else None
            for i, (g, ms, cs) in enumerate(zip(leaves, mem, comp,
                                                strict=True)):
                comp_i, mem_i, cm_i = (triads[i] if triads is not None
                                       else (compressor_, memory,
                                             communicator))
                rng = jax.random.fold_in(step_key, i)
                out, ms, cs = cm_i.step(g, ms, cs, mem_i, comp_i, rng)
                outs.append(out)
                new_mem.append(ms)
                new_comp.append(cs)
        return tuple(outs), tuple(new_mem), tuple(new_comp)

    def _run_dense(operand):
        """Escape hatch: dense ``escape``-coded psum all-reduce of the raw
        gradients; mem/comp pass through untouched so error feedback resumes
        exactly where it paused when compression re-arms."""
        from grace_tpu.comm import Allreduce
        from grace_tpu.telemetry.scopes import STAGE_DENSE_ESCAPE

        leaves, mem, comp, step_key = operand
        allreduce = Allreduce(axis_name=communicator.axis_name)
        outs = []
        with trace_stage(STAGE_DENSE_ESCAPE):
            for i, g in enumerate(leaves):
                rng = jax.random.fold_in(step_key, i)
                payload, ctx, _ = escape.compress(g, escape.init_state(g),
                                                  rng)
                out = allreduce.exchange(payload, ctx, escape)
                outs.append(out.astype(jnp.result_type(g)))
        return tuple(outs), mem, comp

    # -- telemetry ----------------------------------------------------------

    _wire_plan_cache: dict = {}
    # Trace-time cell: the per-leaf route plan of the update being traced
    # (triads aligned with the flattened leaves). Set by update() before
    # the escape cond so both branches (and the telemetry pricing) read
    # one consistent plan; pure Python state, never traced.
    _route_plan: list = [None]

    def _routed_wire_plan(leaves, world):
        """Routed twin of ``_wire_plan``: dense/link/escape/negotiation
        prices summed per leaf through each leaf's OWN codec and
        communicator — the sum-of-per-leaf-prices contract the static
        auditor's wire reconciliation holds routed configs to."""
        from grace_tpu.comm import Allreduce
        from grace_tpu.utils.metrics import payload_nbytes

        triads = _route_plan[0]
        topo = resolved_topology
        structs = [jax.ShapeDtypeStruct(tuple(jnp.shape(l)),
                                        jnp.result_type(l)) for l in leaves]
        dense = n_elems = ici = dcn = wan = neg_b = 0
        for s, (comp_i, _mem_i, cm_i) in zip(structs, triads):
            ne = int(np.prod(s.shape, dtype=np.int64))
            dense += ne * s.dtype.itemsize
            n_elems += ne
            vote_i = bool(getattr(comp_i, "vote_aggregate", False))
            lb = cm_i.recv_link_bytes(payload_nbytes(comp_i, s), ne, world,
                                      topology=topo, vote=vote_i)
            ici += lb.ici
            dcn += lb.dcn
            wan += lb.wan
            neg_b += negotiation_bytes_for(comp_i, ne, world)
        link = LinkBytes(ici=ici, dcn=dcn, wan=wan)
        if escape is not None:
            esc_b = sum(payload_nbytes(escape, s) for s in structs)
            esc_link = Allreduce(
                axis_name=communicator.axis_name).recv_link_bytes(
                    esc_b, n_elems, world, topology=topo)
        else:
            esc_link = None
        return dense, link, esc_link, neg_b

    def _bound_axis_size(axis_name) -> int:
        """Static world size when the mesh axis is bound (inside
        shard_map/pjit, the normal train-step case); 1 when it is not
        (single-process use, e.g. the Identity communicator outside a
        mesh)."""
        try:
            return int(axis_size(axis_name))
        except NameError:       # unbound axis name
            return 1

    def _wire_plan(leaves, world, codec: Optional[Compressor] = None):
        """(dense, link, escape_link, negotiation) logical bytes for these
        leaves under the active fusion mode at world size ``world``.
        ``negotiation`` is the shared-scale negotiation collectives' cost
        (``Compressor.negotiation_nbytes`` × one ``negotiate`` pmax per
        compress call of the fusion plan; 0 for every other codec) —
        surfaced as the ``negotiation_bytes`` telemetry field and folded
        into the effective wire accounting like ``watch_bytes``, since the
        pmax is a real flat full-axis collective. ``dense`` is the
        raw dense gradient bytes (the codec- and communicator-blind
        reference); ``link``/``escape_link`` are COMMUNICATOR-AWARE
        per-link :class:`~grace_tpu.core.LinkBytes` splits of the bytes
        received per rank per step (``Communicator.recv_link_bytes`` under
        the transform's topology; ``link.total`` is the scalar
        ``recv_wire_bytes`` model) — payload bytes alone cannot rank e.g.
        ring/two-shot's O(k) against allgather's O(W·k) received, and the
        scalar alone cannot show that a flat schedule's bytes all ride DCN
        beyond one slice. Static Python ints, cached per (leaf signature,
        world) — eval_shape tracing inside ``payload_nbytes`` is a
        trace-time cost paid once per shape set, never at run time. Same
        logical-vs-padded-bytes caveat as
        :func:`grace_tpu.utils.metrics.wire_report`."""
        from grace_tpu.utils.metrics import payload_nbytes

        compressor_ = codec if codec is not None else compressor
        if routes:
            # Per-leaf routed pricing; uncached (the plan depends on leaf
            # paths, not just shapes — and this is trace-time-only cost).
            return _routed_wire_plan(leaves, world)
        sig = tuple((tuple(jnp.shape(l)), str(jnp.result_type(l)))
                    for l in leaves)
        plan = _wire_plan_cache.get((sig, world, compressor_))
        if plan is not None:
            return plan
        structs = [jax.ShapeDtypeStruct(shape, jnp.dtype(d))
                   for shape, d in sig]
        dense, comp_b, n_elems = fusion_payload_nbytes(
            compressor_, structs, fusion)
        vote = bool(getattr(compressor_, "vote_aggregate", False))
        topo = resolved_topology
        if isinstance(fusion, int) and not isinstance(fusion, bool):
            # The bucketed executor issues one collective CHAIN per bucket,
            # so the honest model is the sum of per-bucket prices, not one
            # whole-payload call: for linear schedules (gather/psum) the
            # two are identical, but ring/two-shot floor-round per
            # collective — K separate exchanges really do move the
            # per-bucket-rounded bytes. Pinned against the per-bucket sum
            # in tests/test_bucketed.py; still inside WIRE_MODEL_RTOL of
            # the whole-payload recv_wire_bytes the auditor reconciles.
            from grace_tpu.utils.metrics import payload_nbytes
            ici = dcn = wan = 0
            for s, count in fusion_payload_structs(structs, fusion):
                b_elems = int(np.prod(s.shape, dtype=np.int64))
                lb = communicator.recv_link_bytes(
                    payload_nbytes(compressor_, s), b_elems, world,
                    topology=topo, vote=vote)
                ici += count * lb.ici
                dcn += count * lb.dcn
                wan += count * lb.wan
            link = LinkBytes(ici=ici, dcn=dcn, wan=wan)
        else:
            link = communicator.recv_link_bytes(comp_b, n_elems, world,
                                                topology=topo, vote=vote)
        if escape is not None:
            from grace_tpu.comm import Allreduce
            esc_b = sum(payload_nbytes(escape, s) for s in structs)
            # The escape hatch is a dense psum all-reduce of the escape
            # payload — price it with the Allreduce ring model (a flat
            # schedule: its split is all-ICI or all-DCN under ``topo``).
            esc_link = Allreduce(
                axis_name=communicator.axis_name).recv_link_bytes(
                    esc_b, n_elems, world, topology=topo)
        else:
            esc_link = None
        # One negotiation collective per compress call the fusion plan
        # issues (per bucket/leaf/group) — zero for codecs without one,
        # leaf-size-aware for index negotiations (cyclic Top-K).
        neg_b = sum(count * negotiation_bytes_for(
            compressor_, int(np.prod(s.shape, dtype=np.int64)), world)
            for s, count in fusion_payload_structs(structs, fusion))
        plan = _wire_plan_cache[(sig, world, compressor_)] = (
            dense, link, esc_link, neg_b)
        return plan

    def _sqsum(ls) -> jax.Array:
        tot = jnp.zeros((), jnp.float32)
        for l in ls:
            if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.inexact):
                tot = tot + jnp.sum(jnp.square(l.astype(jnp.float32)))
        return tot

    def _codec_error_sq(leaves, comp, step_key,
                        codec: Optional[Compressor] = None) -> jax.Array:
        """Σ‖x − decompress(compress(x))‖² over the exact structures (and
        rng derivation) the active fusion mode compresses — so with no
        error-feedback memory the duplicate compress CSEs against the
        pipeline's own. ``codec`` overrides the base compressor (the
        graft-adapt ladder measures the ACTIVE rung's error)."""
        compressor_ = codec if codec is not None else compressor
        diff = jnp.zeros((), jnp.float32)
        if grouped:
            for gi, idxs in enumerate(_group_views(leaves)):
                stacked = jnp.stack([leaves[i] for i in idxs])
                keys = jax.random.split(
                    jax.random.fold_in(step_key, gi), len(idxs))

                def roundtrip(g, cs, key):
                    payload, ctx, _ = compressor_.compress(g, cs, key)
                    return compressor_.decompress(payload, ctx)

                dec = jax.vmap(roundtrip)(stacked, comp[gi], keys)
                diff = diff + _sqsum([stacked - dec])
        elif fused:
            buckets, cdtype = _bucket_views(leaves)
            for b, idxs in enumerate(buckets):
                flat = jnp.concatenate([jnp.ravel(leaves[i]).astype(cdtype)
                                        for i in idxs])
                payload, ctx, _ = compressor_.compress(
                    flat, comp[b], jax.random.fold_in(step_key, b))
                diff = diff + _sqsum([flat
                                      - compressor_.decompress(payload,
                                                               ctx)])
        else:
            triads = _route_plan[0] if routes else None
            for i, g in enumerate(leaves):
                comp_i = (triads[i][0] if triads is not None
                          else compressor_)
                payload, ctx, _ = comp_i.compress(
                    g, comp[i], jax.random.fold_in(step_key, i))
                diff = diff + _sqsum([g - comp_i.decompress(payload, ctx)])
        return diff

    def _telemetry_next(state: GraceState, leaves, outs, new_mem, step_key,
                        err_value=None, eff_idx=None):
        """One telemetry row, written at slot count % capacity, plus the
        maybe-updated graft-watch summary ring. The row itself is pure
        in-graph math over values the step already computed (plus the
        optional codec round-trip) — no collectives, no host syncs; the
        watch summary (when armed) adds exactly one tiny all_gather on
        window-boundary steps, whose wire cost is folded into this row.

        With graft-adapt armed, ``eff_idx`` is the replicated EFFECTIVE
        rung this step's exchange ran at and ``err_value`` the active
        rung's relative compression error (already 0 on the dense rung):
        the row's effective wire bytes then come from a per-rung wire
        plan indexed by ``eff_idx`` — the dense-fallback byte flip
        generalized to R rungs, ici/dcn split included — and the rung
        plus the signal reductions' cost are surfaced as
        ``adapt_rung``/``adapt_bytes``."""
        if state.telem is None:
            raise ValueError(
                "grace_transform was built with telemetry=... but the state "
                "has no telemetry ring — it was initialized by a transform "
                "without telemetry (or restored from such a checkpoint). "
                "Re-init the optimizer state with the telemetry-enabled "
                "transform.")
        dense_b, link, esc_link, neg_b = _wire_plan(
            leaves, _bound_axis_size(communicator.axis_name))
        comp_b, esc_b = link.total, (
            esc_link.total if esc_link is not None else None)
        grad_norm = jnp.sqrt(_sqsum(leaves))
        update_norm = jnp.sqrt(_sqsum(outs))
        mem_leaves = [l for l in jax.tree_util.tree_leaves(new_mem)
                      if hasattr(l, "dtype")
                      and jnp.issubdtype(l.dtype, jnp.inexact)]
        residual_norm = jnp.sqrt(_sqsum(mem_leaves))
        residual_max = (jnp.max(jnp.stack(
            [jnp.max(jnp.abs(l.astype(jnp.float32))) for l in mem_leaves]))
            if mem_leaves else jnp.zeros((), jnp.float32))
        if telemetry.compression_error:
            if err_value is not None:
                # graft-adapt: the active rung's error, computed once in
                # update() (shared with the controller's signal) — 0 on
                # the dense rung by construction, which subsumes the
                # fallback-window zeroing below.
                err = jnp.asarray(err_value, jnp.float32)
            else:
                err = jnp.sqrt(_codec_error_sq(leaves, state.comp,
                                               step_key)) \
                    / jnp.maximum(grad_norm,
                                  jnp.asarray(1e-20, jnp.float32))
                if escape is not None:
                    # During a dense window the codec is bypassed: the
                    # *effective* error of what actually shipped is ~0.
                    err = jnp.where(jnp.asarray(state.fallback, jnp.bool_),
                                    jnp.zeros((), jnp.float32), err)
        else:
            err = jnp.zeros((), jnp.float32)
        if eff_idx is not None:
            # Per-rung effective wire plan (graft-adapt): static prices
            # for every reachable rung — rung 0 is the escape psum, rung
            # r >= 1 the ladder codec's plan through the same
            # communicator — selected by the replicated effective rung.
            # The guard's fallback flag forces eff_idx to 0 upstream, so
            # the dense-fallback flip is the same mechanism.
            from grace_tpu.resilience.adapt import adapt_signal_bytes
            world = _bound_axis_size(communicator.axis_name)
            rung_plans = [_wire_plan(leaves, world, codec=c)
                          for c in adapt.ladder]
            rung_tot = jnp.asarray(
                [float(esc_link.total)]
                + [float(p[1].total) for p in rung_plans], jnp.float32)
            rung_ici = jnp.asarray(
                [float(esc_link.ici)]
                + [float(p[1].ici) for p in rung_plans], jnp.float32)
            rung_dcn = jnp.asarray(
                [float(esc_link.dcn)]
                + [float(p[1].dcn) for p in rung_plans], jnp.float32)
            rung_wan = jnp.asarray(
                [float(esc_link.wan)]
                + [float(p[1].wan) for p in rung_plans], jnp.float32)
            rung_neg = jnp.asarray(
                [0.0] + [float(p[3]) for p in rung_plans], jnp.float32)
            eff = rung_tot[eff_idx]
            eff_ici = rung_ici[eff_idx]
            eff_dcn = rung_dcn[eff_idx]
            eff_wan = rung_wan[eff_idx]
            ngb = rung_neg[eff_idx]
            # The signal reductions run every step — two scalar
            # full-axis collectives, folded like watch_bytes (flat
            # schedule: ICI within one slice, DCN beyond, WAN beyond one
            # region — Topology.flat_tier).
            ab = jnp.asarray(float(adapt_signal_bytes(world)), jnp.float32)
            tier = resolved_topology.flat_tier(world)
            eff = eff + ngb + ab
            if tier == "wan":
                eff_wan = eff_wan + ngb + ab
            elif tier == "dcn":
                eff_dcn = eff_dcn + ngb + ab
            else:
                eff_ici = eff_ici + ngb + ab
        elif escape is None:
            eff = jnp.asarray(float(comp_b), jnp.float32)
            eff_ici = jnp.asarray(float(link.ici), jnp.float32)
            eff_dcn = jnp.asarray(float(link.dcn), jnp.float32)
            eff_wan = jnp.asarray(float(link.wan), jnp.float32)
        else:
            fb = jnp.asarray(state.fallback, jnp.bool_)
            eff = jnp.where(fb, jnp.asarray(float(esc_b), jnp.float32),
                            jnp.asarray(float(comp_b), jnp.float32))
            # The per-link split flips with the scalar: a dense-fallback
            # window's bytes ride the escape psum's flat schedule.
            eff_ici = jnp.where(
                fb, jnp.asarray(float(esc_link.ici), jnp.float32),
                jnp.asarray(float(link.ici), jnp.float32))
            eff_dcn = jnp.where(
                fb, jnp.asarray(float(esc_link.dcn), jnp.float32),
                jnp.asarray(float(link.dcn), jnp.float32))
            eff_wan = jnp.where(
                fb, jnp.asarray(float(esc_link.wan), jnp.float32),
                jnp.asarray(float(link.wan), jnp.float32))
        if eff_idx is None:
            # Shared-scale negotiation cost, folded like watch_bytes —
            # into the scalar AND the per-link split (the pmax is a flat
            # full-axis collective), zeroed during dense-fallback windows
            # (the dense branch never negotiates). The adapt path above
            # already selected a per-rung negotiation price instead.
            ab = jnp.zeros((), jnp.float32)
            ngb = jnp.asarray(float(neg_b), jnp.float32)
            if escape is not None:
                ngb = jnp.where(jnp.asarray(state.fallback, jnp.bool_),
                                jnp.zeros((), jnp.float32), ngb)
            if neg_b:
                world = _bound_axis_size(communicator.axis_name)
                tier = resolved_topology.flat_tier(world)
                eff = eff + ngb
                if tier == "wan":
                    eff_wan = eff_wan + ngb
                elif tier == "dcn":
                    eff_dcn = eff_dcn + ngb
                else:
                    eff_ici = eff_ici + ngb
        new_watch = state.watch
        wb = jnp.zeros((), jnp.float32)
        if watch is not None:
            if state.watch is None:
                raise ValueError(
                    "grace_transform was built with watch=... but the "
                    "state has no watch ring — it was initialized by a "
                    "transform without watch (or restored from such a "
                    "checkpoint). Re-init the optimizer state with the "
                    "watch-enabled transform.")
            with trace_stage(STAGE_WATCH):
                world = _bound_axis_size(communicator.axis_name)
                due = jnp.equal(jnp.mod(state.count, watch.window), 0)
                new_watch = watch_record(
                    state.watch, state.count,
                    {"grad_norm": grad_norm, "compression_error": err,
                     "residual_norm": residual_norm},
                    communicator.axis_name, due)
                # Fold the gather's received bytes into the effective wire
                # accounting — the same honesty contract as audit_bytes,
                # but split by link too: the health gather is a flat
                # full-axis collective, so it rides ICI within one slice,
                # DCN beyond it, and WAN beyond one region — exactly like
                # the escape psum (Topology.flat_tier).
                tier = resolved_topology.flat_tier(world)
                wb = jnp.where(due, jnp.asarray(
                    float(watch_gather_bytes(world)), jnp.float32), 0.0)
                eff = eff + wb
                if tier == "wan":
                    eff_wan = eff_wan + wb
                elif tier == "dcn":
                    eff_dcn = eff_dcn + wb
                else:
                    eff_ici = eff_ici + wb
        return new_watch, telemetry_record(state.telem, state.count, {
            "grad_norm": grad_norm,
            "update_norm": update_norm,
            "residual_norm": residual_norm,
            "residual_max": residual_max,
            "compression_error": err,
            "wire_bytes": eff,
            "dense_bytes": jnp.asarray(float(dense_b), jnp.float32),
            "fallback": jnp.asarray(state.fallback, jnp.float32),
            # Filled in after the fact by consensus_step on audit steps —
            # the audit runs post-apply, after this row is written.
            "audit_bytes": jnp.zeros((), jnp.float32),
            # Per-link split of the exchange's wire_bytes under the
            # transform's Topology; ici + dcn + wan == wire_bytes on every
            # non-audit step (the consensus hook folds its flat-collective
            # audit cost into the scalar only; the watch gather is folded
            # into scalar AND split, so the identity survives it).
            "wire_bytes_ici": eff_ici,
            "wire_bytes_dcn": eff_dcn,
            "wire_bytes_wan": eff_wan,
            "watch_bytes": wb,
            "negotiation_bytes": ngb,
            # graft-adapt: the effective rung this row's bytes were
            # priced at (-1 = controller not armed) and the signal
            # reductions' cost (folded into wire_bytes AND the split,
            # like watch_bytes).
            "adapt_rung": (eff_idx.astype(jnp.float32)
                           if eff_idx is not None
                           else jnp.asarray(-1.0, jnp.float32)),
            "adapt_bytes": ab,
        })

    def update(updates, state: GraceState, params=None):
        del params
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        if routes:
            _route_plan[0] = _leaf_triads(updates)[1]
        base_key = jax.random.wrap_key_data(state.rng_key)
        step_key = jax.random.fold_in(base_key, state.count)
        operand = (tuple(leaves), state.mem, state.comp, step_key)
        eff_idx = local_err = None
        adapt_state = state.adapt
        if adapt is not None:
            # graft-adapt ladder dispatch: one lax.switch over every
            # reachable rung — branch 0 is the dense escape (the guard's
            # fallback flag forces it, so the M-step dense window is this
            # same branch), branch r the ladder's rung-r codec through
            # the unchanged memory/communicator/fusion plan. The index is
            # replicated by construction (the commanded rung is policy
            # state derived from full-axis reductions; the fallback flag
            # is the guard's replicated verdict), which is the exact
            # predicate contract lint pass 1 verifies — every rank takes
            # the same branch and the rung's collectives rendezvous.
            if state.adapt is None:
                raise ValueError(
                    "grace_transform was built with adapt=... but the "
                    "state has no AdaptState — it was initialized by a "
                    "transform without adapt (or restored from such a "
                    "checkpoint). Re-init the optimizer state with the "
                    "adapt-enabled transform.")
            from grace_tpu.resilience.adapt import (adapt_advance,
                                                    adapt_signal)
            from grace_tpu.telemetry.scopes import STAGE_ADAPT
            top = len(adapt.ladder)
            fb = jnp.asarray(state.fallback, jnp.bool_)
            eff_idx = jnp.where(
                fb, jnp.zeros((), jnp.int32),
                jnp.clip(jnp.asarray(state.adapt.rung, jnp.int32), 0,
                         top)).astype(jnp.int32)
            branches = [_run_dense] + [
                (lambda op, c=c: _run_compressed(op, codec=c))
                for c in adapt.ladder]
            try:
                outs, new_mem, new_comp = lax.switch(eff_idx, branches,
                                                     operand)
            except TypeError as e:
                raise ValueError(
                    "adapt ladder rungs must thread identical mem/comp "
                    "state structures (the lax.switch branches return one "
                    "state type) — a rung whose compressor state changes "
                    "shape per rung cannot ride one ladder. PowerSGD rank "
                    "ladders need a uniform padded state: set state_rank "
                    "to the ladder's max rank on every rung "
                    "(grace_from_params does this automatically): "
                    f"{e}") from None
            # The controller's signal + advance: the ACTIVE rung's local
            # relative compression error (0 on the dense rung — nothing
            # lossy shipped), reduced to a replicated (mean, worst-rank)
            # pair with one scalar pmean + pmax, accumulated into the
            # replicated window statistics, and decided at the window
            # boundary (the consensus/watch lax.cond idiom).
            grad_norm = jnp.sqrt(_sqsum(leaves))
            err_ops = (tuple(leaves), state.comp, step_key)
            err_branches = [lambda op: jnp.zeros((), jnp.float32)] + [
                (lambda op, c=c: jnp.sqrt(_codec_error_sq(
                    op[0], op[1], op[2], codec=c))
                 / jnp.maximum(grad_norm, jnp.asarray(1e-20, jnp.float32)))
                for c in adapt.ladder]
            with trace_stage(STAGE_ADAPT):
                local_err = lax.switch(eff_idx, err_branches, err_ops)
                err_mean, err_peak = adapt_signal(local_err,
                                                  communicator.axis_name)
                adapt_state = adapt_advance(state.adapt, adapt,
                                            state.count, state.fallback,
                                            err_mean, err_peak)
        elif escape is None:
            outs, new_mem, new_comp = _run_compressed(operand)
        else:
            # Both branches carry collectives; the predicate is replicated
            # (the guard derives it from rank-identical post-exchange
            # updates, OR-reduced over the axis), so every rank takes the
            # same branch and the collectives rendezvous.
            outs, new_mem, new_comp = lax.cond(
                jnp.asarray(state.fallback, jnp.bool_),
                _run_dense, _run_compressed, operand)
        telem, watch_state = state.telem, state.watch
        if telemetry is not None:
            with trace_stage(STAGE_TELEMETRY):
                watch_state, telem = _telemetry_next(state, leaves, outs,
                                                     new_mem, step_key,
                                                     err_value=local_err,
                                                     eff_idx=eff_idx)
        new_state = GraceState(count=state.count + 1, rng_key=state.rng_key,
                               mem=new_mem, comp=new_comp,
                               fallback=state.fallback, telem=telem,
                               audit=state.audit, watch=watch_state,
                               adapt=adapt_state)
        return jax.tree_util.tree_unflatten(treedef, outs), new_state

    # The one resolved topology object both pricing paths close over —
    # exposed so tests can pin the single-invalidation-point contract
    # (None when telemetry is off: nothing prices a per-link split).
    update.grace_topology = resolved_topology
    # The mesh layout and route table the transform was built under —
    # read by the static auditor's tracer (2-D replication seeding) and
    # the routed wire reconciliation.
    update.grace_mesh = mesh
    update.grace_routes = routes
    return optax.GradientTransformation(init, update)
