"""Mesh construction and multi-host initialization helpers.

TPU-native replacement for the reference's cluster bring-up: ``hvd.init()``
(MPI topology, examples/torch/pytorch_mnist.py:50) and
``dist.init_process_group('nccl', 'tcp://…')``
(examples/dist/CIFAR10-dawndist/core.py:225-226). On TPU, process discovery
and ICI/DCN topology come from `jax.distributed.initialize` + the device
mesh; collectives ride ICI within a slice and DCN across slices with no
NCCL/MPI anywhere.

The default mesh is 1-D over axis ``'data'`` — GRACE's scope is exactly
synchronous data parallelism (SURVEY.md §2.5) — but axes are named so model/
sequence axes can be added later without API change.
"""

from __future__ import annotations

import os
import sys
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from grace_tpu.core import DEFAULT_AXIS

__all__ = ["DEFAULT_AXIS", "data_parallel_mesh", "make_mesh",
           "initialize_distributed", "replicated", "batch_sharded",
           "local_world_size", "broadcast_tree", "metric_average",
           "relax_cpu_collective_timeouts", "shard_map",
           "set_cpu_device_count"]


def set_cpu_device_count(n: int) -> None:
    """Request ``n`` simulated XLA:CPU host devices, across JAX versions.

    Newer JAX spells this ``jax.config.update('jax_num_cpu_devices', n)``;
    older releases (e.g. 0.4.37) only honor the
    ``--xla_force_host_platform_device_count`` XLA flag. Either way it must
    run before the CPU backend initializes (before the first
    ``jax.devices()``/array creation) — importing jax earlier is fine.
    """
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}").strip()


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """`jax.shard_map` across JAX versions.

    JAX promoted shard_map to the top-level namespace (with the replication
    check renamed ``check_vma``) after 0.4.x; on older releases (e.g. the
    0.4.37 this image ships) the only spelling is
    ``jax.experimental.shard_map.shard_map(..., check_rep=...)``. Every
    shard_map in grace-tpu goes through this wrapper so the rest of the
    codebase can use the modern keyword unconditionally.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)


def relax_cpu_collective_timeouts(warn_s: int = 300,
                                  terminate_s: int = 1200) -> None:
    """Raise XLA:CPU's in-process collective rendezvous timeouts.

    The simulated N-device CPU mesh runs each "device" as a host thread; on
    a host with few cores (this dev image has ONE) a heavy step can keep
    half the device threads from reaching an all-reduce rendezvous within
    XLA's default 20s warn / 40s terminate window, which kills the process
    mid-collective (seen: LeNet/MNIST on the 8-device mesh). XLA reads
    these flags from $XLA_FLAGS at backend initialization, so call this
    before the first `jax.devices()` — importing jax earlier is fine.
    No-op for flags the caller already set explicitly.
    """
    import os

    import jaxlib

    try:
        jaxlib_ver = tuple(int(p) for p in
                           jaxlib.__version__.split(".")[:2])
    except Exception:
        jaxlib_ver = (0, 0)
    if jaxlib_ver < (0, 5):
        # XLA:CPU in jaxlib < 0.5 does not know these flags, and XLA
        # hard-aborts the whole process on unknown XLA_FLAGS entries
        # (parse_flags_from_env F-check) — worse than the stuck-collective
        # warning the flags would relax. Skip on old runtimes.
        return

    flags = os.environ.get("XLA_FLAGS", "")
    extra = []
    if "--xla_cpu_collective_call_warn_stuck_timeout_seconds" not in flags:
        extra.append("--xla_cpu_collective_call_warn_stuck_timeout_seconds"
                     f"={warn_s}")
    if "--xla_cpu_collective_call_terminate_timeout_seconds" not in flags:
        extra.append("--xla_cpu_collective_call_terminate_timeout_seconds"
                     f"={terminate_s}")
    if extra:
        os.environ["XLA_FLAGS"] = " ".join([flags, *extra]).strip()


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (replaces hvd.init / init_process_group).

    On Cloud TPU all arguments are auto-detected from the metadata server;
    pass them explicitly for other clusters. Must run before any JAX
    computation (do NOT touch jax.devices()/process_count() first — that
    initializes the local backend and forecloses cluster bring-up).

    With no arguments and no detectable cluster environment this is a no-op
    (single-process run). With explicit arguments, failures propagate: a
    mis-configured multi-host job must die loudly rather than silently train
    as independent single-host replicas.
    """
    if coordinator_address is None and num_processes is None and process_id is None:
        # Markers that say "this process believes it is part of a cluster".
        # If any is set, an auto-init failure means a MIS-configured cluster
        # (e.g. SLURM_JOB_ID without the rank/size vars) — dying loudly
        # beats silently training as independent single-process replicas.
        # Only a genuinely marker-free environment downgrades to a no-op.
        markers = [v for v in ("SLURM_JOB_ID", "SLURM_PROCID",
                               "OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE",
                               "PMI_RANK", "PMI_SIZE",
                               "JAX_COORDINATOR_ADDRESS",
                               "MEGASCALE_COORDINATOR_ADDRESS")
                   if os.environ.get(v) is not None]
        try:
            jax.distributed.initialize()
        except Exception as e:
            if markers:
                raise RuntimeError(
                    f"cluster environment markers {markers} are set but "
                    f"jax.distributed.initialize() failed — refusing to "
                    f"fall back to a single-process run") from e
            print(f"[grace-tpu] no cluster environment auto-detected "
                  f"({type(e).__name__}: {e}); single-process run",
                  file=sys.stderr)
            return
    else:
        jax.distributed.initialize(coordinator_address, num_processes, process_id)


def data_parallel_mesh(devices: Optional[Sequence[jax.Device]] = None,
                       axis_name: str = DEFAULT_AXIS) -> Mesh:
    """1-D mesh over all (global) devices — the GRACE data-parallel world."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def make_mesh(shape: Sequence[int], axis_names: Sequence[str],
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """N-D mesh for layouts beyond pure DP (e.g. ('data', 'model'))."""
    devices = list(devices) if devices is not None else jax.devices()
    arr = np.asarray(devices).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis_name: str = DEFAULT_AXIS) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(axis_name))


def local_world_size(mesh: Mesh, axis_name: str = DEFAULT_AXIS) -> int:
    return mesh.shape[axis_name]


def broadcast_tree(tree, root_process: int = 0):
    """Broadcast a host pytree from one process to all (multi-host init sync).

    The pure-JAX analog of the reference's init-time parameter broadcast
    (examples/torch/pytorch_mnist.py:116 ``hvd.broadcast_parameters``, and
    the BroadcastGlobalVariablesCallback of
    examples/tensorflow/tensorflow2_keras_mnist.py:73). Initializing params
    from the same seed on every process already makes replicas identical by
    construction; use this when init is *not* deterministic across hosts
    (e.g. restored from a host-local file) to make the sync explicit.

    Single-process: identity. Multi-process: every leaf is replaced by
    ``root_process``'s value on all hosts.
    """
    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(
        tree, is_source=jax.process_index() == root_process)


def metric_average(metrics):
    """Average a host-side metrics pytree across processes.

    The reference's ``metric_average`` idiom
    (examples/torch/pytorch_mnist.py:163-166: allreduce a scalar, return the
    mean). For metrics computed inside a jitted eval step prefer
    :func:`grace_tpu.train.make_eval_step`, which pmeans on-device; this
    helper is for host-side values (e.g. per-process validation accuracy
    over a host-sharded eval set).
    """
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(lambda x: np.asarray(x), metrics)
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(metrics)
    return jax.tree_util.tree_map(
        lambda g: np.mean(np.asarray(g), axis=0), gathered)
