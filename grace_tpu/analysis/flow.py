"""graft-flow: dependence-graph static analysis over traced configs.

The four original graft-lint passes (:mod:`grace_tpu.analysis.passes`) walk
the jaxpr equation-by-equation; none of them can answer *ordering*
questions — what must wait on what. This module adds the dependence-graph
layer: :func:`build_depgraph` flattens a
:class:`~grace_tpu.analysis.trace.TracedGraph` into one equation-level DAG
(ancestor bitsets over every nested cond/pjit/while body, gradient-root
tracking seeded from the tracer's outer-argument map) and three passes ride
on top of it:

* ``overlap_schedulability`` — for every collective, the set of
  data-independent compute equations is a **static upper bound** on the
  overlap fraction graft-prof measures from device timelines
  (:mod:`grace_tpu.profiling.trace_analysis` — measured can never exceed
  what the dataflow permits, so ``measured > static bound`` means the
  attribution is lying and is flagged). It also counts the *independent
  compress→exchange chains* the exchange stage exposes: with
  ``fusion=<bytes>`` bucketing the plan promises K buckets, and a graph
  where one bucket's exchange transitively depends on another bucket's is
  a serialization point XLA's latency-hiding scheduler cannot undo — the
  forcing function for ROADMAP item 2's chunked bucket scheduling.
* ``numeric_safety`` — value-range abstract interpretation over payload
  dtypes: a per-rank payload term has unit multiplicity, hop sums and adds
  accumulate multiplicities, ``psum``/grouped collectives multiply by the
  ranks they span, and a float dtype whose accumulated term count exceeds
  ``finfo(dtype).max / NUMERIC_UNIT_MAG`` is a silent-saturation finding
  (fp16's 65504 cliff at W=4096; bf16 has no cliff and never fires). Vote
  psums (the ``psum_vote`` trace scope) are checked against
  :func:`grace_tpu.comm.vote_exact_max_world` — the same first-principles
  constant the runtime guard in ``comm._psum_majority_vote`` enforces, so
  the static pass and the runtime check can never disagree. Codec payload
  contracts ride along: selection-index dtypes must address the fused leaf
  sizes, and sub-byte bit-packing (:mod:`grace_tpu.ops.packing`) must
  round-trip its declared widths.
* ``memory_footprint`` — eval_shape-based per-rank accounting of the
  GraceState rings (mem/comp/telem/bookkeeping, literally
  :func:`grace_tpu.profiling.grace_state_footprint` — the static twin of
  the recorder's live check) reconciled against the traced state
  signature, plus peak wire-buffer accounting from the traced collective
  outputs, flagging replicated state buffers whose shape scales with the
  world size (per-rank O(W), fleet-wide O(W²)).

All three register with :func:`grace_tpu.analysis.passes.run_passes` (the
names appear in ``PASS_NAMES``; the module itself loads lazily to keep the
import graph acyclic), run over the full config registry, and are proven
live on seeded-bad graphs in ``tests/test_flow.py``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from grace_tpu.analysis.passes import (COLLECTIVE_PRIMS, Finding,
                                       _REDUCTIONS, _aval_nbytes, _axes_of,
                                       _group_size, _is_var, _stage_of,
                                       _sub_jaxprs_of)
from grace_tpu.analysis.trace import TracedGraph, default_param_structs
from grace_tpu.telemetry.scopes import STAGE_EXCHANGE

__all__ = ["DepNode", "DepGraph", "build_depgraph", "overlap_summary",
           "footprint_report", "footprint_model", "safe_sum_terms",
           "NUMERIC_UNIT_MAG", "OVERLAP_SLACK",
           "pass_overlap_schedulability", "pass_numeric_safety",
           "pass_memory_footprint"]

FLOW_PASS_NAMES = ("overlap_schedulability", "numeric_safety",
                   "memory_footprint")

# Slack on the measured-vs-static overlap comparison: graft-prof's interval
# unions carry trace-clock jitter and the static compute-cost proxy is
# byte-weighted, so only a measured overlap that beats the static bound by
# more than this is called a lie (same ±0.05 absolute band perf_report's
# baseline gate uses for overlap regressions).
OVERLAP_SLACK = 0.05

# The documented per-term magnitude budget of the numeric-safety range
# analysis: one rank's payload element is assumed bounded by this many
# units. 256 covers every codec in the catalog with headroom (qsgd codes
# are <= quantum_num <= 256 scaled by a norm the codec carries separately;
# sign/vote terms are +-1; fp16/topk values are gradient-magnitude, and a
# gradient element above 256 is already a divergence the guard owns). The
# analysis is linear — accumulating W such terms reaches W*256 — so the
# safe term count for a dtype is finfo.max / 256: ~255 for fp16 (the 65504
# cliff), ~10^36 for fp32/bf16 (no cliff at any real W).
NUMERIC_UNIT_MAG = 256.0


def safe_sum_terms(dtype) -> Optional[int]:
    """How many unit-magnitude payload terms a float dtype can accumulate
    before overflowing: ``floor(finfo.max / NUMERIC_UNIT_MAG)``. None for
    non-float dtypes (integer reductions are the bit-exactness pass's
    sanctioned space — masked broadcasts deliberately sum W-1 zeros)."""
    dt = np.dtype(dtype) if not hasattr(dtype, "itemsize") else dtype
    import jax.numpy as jnp

    if not jnp.issubdtype(dt, jnp.floating):
        return None
    return int(float(jnp.finfo(dt).max) / NUMERIC_UNIT_MAG)


def _raw_stack(eqn) -> str:
    try:
        return str(eqn.source_info.name_stack)
    except Exception:
        return ""


_BUCKET_RE = None
_PIPE_RE = None


def _bucket_of(eqn) -> Optional[str]:
    """The chain-scope id an equation was traced under, or None: the
    bucketed executor's ``grace/bucket/<b>`` tag, the ring schedules'
    double-buffered ``grace/pipeline/<p>`` segment tag, or both joined —
    each (bucket, segment) pair is its own independent collective chain,
    which is exactly how the chain counting must group heads."""
    global _BUCKET_RE, _PIPE_RE
    if _BUCKET_RE is None:
        import re

        from grace_tpu.telemetry.scopes import STAGE_BUCKET, STAGE_PIPELINE
        _BUCKET_RE = re.compile(re.escape(STAGE_BUCKET) + r"/(\d+)")
        _PIPE_RE = re.compile(re.escape(STAGE_PIPELINE) + r"/(\d+)")
    stack = _raw_stack(eqn)
    tags = [m.group(0) for m in (_BUCKET_RE.search(stack),
                                 _PIPE_RE.search(stack)) if m]
    return "|".join(tags) if tags else None


# ---------------------------------------------------------------------------
# the dependence graph
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DepNode:
    """One flattened equation. ``nbytes`` (total output bytes) is the cost
    proxy both overlap weighting and wire-buffer accounting use; ``roots``
    is a bitmask over the traced graph's gradient inputs this equation
    transitively depends on; ``chain`` is the bucketed executor's
    ``grace/bucket/<b>`` scope id when the equation was traced inside one
    (None elsewhere) — the per-pipeline tag chain counting groups by when
    gradient roots alone cannot separate buckets (a train-step trace: every
    bucket's gradient descends from the same batch inputs)."""

    idx: int
    prim: str
    stage: str
    nbytes: int
    collective: bool
    roots: int = 0
    chain: Optional[str] = None


@dataclasses.dataclass
class DepGraph:
    """Equation-level dependence DAG of one traced config.

    ``anc[i]`` is a bitmask of node indices that are (transitive) ancestors
    of node ``i`` — bitsets keep the reachability closure cheap enough to
    build for every registered config in CI. Nested jaxprs (cond branches,
    pjit bodies, unrolled ring hops) are flattened into the one graph, so
    "independent" always means independent across the whole program, not
    within one sub-jaxpr.
    """

    nodes: List[DepNode]
    anc: List[int]
    n_grad_roots: int

    def is_ancestor(self, a: int, b: int) -> bool:
        """True iff node ``a``'s output (transitively) feeds node ``b``."""
        return bool((self.anc[b] >> a) & 1)


def build_depgraph(traced: TracedGraph) -> DepGraph:
    """Flatten the traced body into one dependence DAG.

    Every equation of every nested jaxpr becomes a node; a node's ancestor
    set is the union of its operands' def chains. Call-like equations
    (``pjit``/``cond``/``while``/``custom_*``) are dissolved — their inner
    equations join the global graph and the call's outputs carry the union
    of the matching inner outputs' masks (conservative positional fallback
    when arities disagree). Gradient roots are the tracer's ``grad_in``
    vars, so ``roots`` says which gradient leaves each equation's value
    descends from — the bucket-independence question.
    """
    nodes: List[DepNode] = []
    anc: List[int] = []
    grad_bit = {v: i for i, v in enumerate(traced.grad_in)}

    env: Dict[Any, Tuple[int, int]] = {}
    for v in traced.body.invars:
        bit = grad_bit.get(v)
        env[v] = (0, (1 << bit) if bit is not None else 0)
    for v in getattr(traced.body, "constvars", ()):
        env[v] = (0, 0)

    def walk(jaxpr, env):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            in_anc = in_root = 0
            for v in eqn.invars:
                if _is_var(v) and v in env:
                    a, r = env[v]
                    in_anc |= a
                    in_root |= r
            subs = _sub_jaxprs_of(eqn)
            if subs and name not in COLLECTIVE_PRIMS:
                branch_outs = []
                for sub in subs:
                    ops = eqn.invars[1:] if name == "cond" else eqn.invars
                    if len(sub.invars) == len(ops):
                        sub_env = {
                            sv: (env.get(ov, (0, 0)) if _is_var(ov)
                                 else (0, 0))
                            for sv, ov in zip(sub.invars, ops)}
                    else:
                        sub_env = {sv: (in_anc, in_root)
                                   for sv in sub.invars}
                    for cv in getattr(sub, "constvars", ()):
                        sub_env[cv] = (0, 0)
                    walk(sub, sub_env)
                    branch_outs.append([
                        sub_env.get(ov, (in_anc, in_root))
                        if _is_var(ov) else (0, 0)
                        for ov in sub.outvars])
                for j, ov in enumerate(eqn.outvars):
                    a, r = in_anc, in_root
                    for outs in branch_outs:
                        if len(outs) == len(eqn.outvars):
                            a |= outs[j][0]
                            r |= outs[j][1]
                    env[ov] = (a, r)
            else:
                idx = len(nodes)
                nbytes = sum(_aval_nbytes(v.aval) for v in eqn.outvars
                             if hasattr(v, "aval"))
                coll = (name in COLLECTIVE_PRIMS
                        and traced.axis_name in _axes_of(eqn))
                nodes.append(DepNode(idx=idx, prim=name,
                                     stage=_stage_of(eqn), nbytes=nbytes,
                                     collective=coll, roots=in_root,
                                     chain=_bucket_of(eqn)))
                anc.append(in_anc)
                out = (in_anc | (1 << idx), in_root)
                for ov in eqn.outvars:
                    env[ov] = out

    walk(traced.body, env)
    return DepGraph(nodes=nodes, anc=anc,
                    n_grad_roots=len(traced.grad_in))


# ---------------------------------------------------------------------------
# pass 5: overlap schedulability
# ---------------------------------------------------------------------------

def overlap_summary(traced: TracedGraph,
                    graph: Optional[DepGraph] = None) -> Dict[str, Any]:
    """The schedulability numbers for one traced config.

    For every collective ``c``: the byte-cost of compute equations that are
    neither ancestors nor descendants of ``c`` — the only work XLA's
    latency-hiding scheduler is *allowed* to run under the exchange. The
    per-collective bound ``min(1, independent_compute / collective_bytes)``
    aggregates (collective-byte weighted) into ``static_overlap_bound``,
    the static upper bound on graft-prof's measured overlap fraction.
    ``independent_chains`` counts *gradient-disjoint* chain heads:
    exchange-stage collectives with no other exchange-stage collective as
    ancestor, grouped by their gradient-root sets — a payload of several
    wire tensors (top-k values + indices, packed codes + norm) is ONE
    chain, not one per tensor, because its collectives all hang off the
    same bucket's gradients; a multi-phase schedule like ring/two-shot is
    likewise one chain (its phases share gradient roots and chain by
    construction). The bucketed executor's K buckets partition the
    gradient leaves, so its chains count exactly K.
    """
    g = graph if graph is not None else build_depgraph(traced)
    computes = [n for n in g.nodes if not n.collective and n.nbytes > 0]
    colls = [n for n in g.nodes if n.collective]
    total_compute = sum(n.nbytes for n in computes)
    per = []
    for c in colls:
        indep = sum(n.nbytes for n in computes
                    if not g.is_ancestor(c.idx, n.idx)
                    and not g.is_ancestor(n.idx, c.idx))
        cost = max(c.nbytes, 1)
        per.append({"prim": c.prim, "stage": c.stage,
                    "collective_bytes": c.nbytes,
                    "independent_compute_bytes": indep,
                    "bound": min(1.0, indep / cost)})
    weight = sum(max(c.nbytes, 1) for c in colls)
    bound = (sum(max(c.nbytes, 1) * p["bound"]
                 for c, p in zip(colls, per)) / weight
             if colls else None)
    ex = [c for c in colls if c.stage == STAGE_EXCHANGE]
    heads = [c for c in ex
             if not any(g.is_ancestor(o.idx, c.idx)
                        for o in ex if o is not c)]
    # Chain identity = (gradient-root set, bucket scope): the root set
    # separates per-leaf/seeded chains, the grace/bucket/<b> tag separates
    # the bucketed executor's pipelines when every bucket's gradient
    # descends from the same inputs (train-step traces — the whole batch
    # feeds the backward). A head with neither (constant-fed bookkeeping)
    # counts as its own chain rather than collapsing unrelated heads.
    chains = {((n.roots if n.roots else ("head", n.idx)), n.chain)
              for n in heads}
    return {"n_collectives": len(colls),
            "exchange_collectives": len(ex),
            "independent_chains": len(chains),
            "total_compute_bytes": total_compute,
            "static_overlap_bound": bound,
            "per_collective": per}


def _expected_chains(traced: TracedGraph) -> Optional[int]:
    """How many independent compress→exchange chains the config promises:
    the ``meta['expected_chains']`` override (seeded tests), else the
    ``fusion=<bytes>`` bucketing plan's bucket count — the one fusion mode
    whose entire purpose is exposing K independent chains (ROADMAP item
    2's chunked bucket scheduling). Other fusion modes promise nothing
    schedulability-shaped: 'flat' is deliberately one chain, per-leaf and
    'grouped' derive their chain count from the model, not a knob."""
    override = traced.meta.get("expected_chains")
    if override is not None:
        return int(override)
    grace = traced.meta.get("grace")
    if grace is None:
        return None
    # The double-buffered ring schedules multiply every bucket's chains by
    # their segment count: P segments each run the whole hop schedule
    # under their own grace/pipeline/<p> scope, independent by
    # construction (contiguous buffer slices). Even flat fusion — one
    # bucket — must then expose P chains, which is the whole point of
    # pipeline > 1; fewer means the segments serialized.
    pipeline = int(getattr(getattr(grace, "communicator", None),
                           "pipeline", 1) or 1)
    fusion = getattr(grace, "fusion", None)
    if not isinstance(fusion, int) or isinstance(fusion, bool):
        return pipeline if pipeline > 1 else None
    from grace_tpu.transform import _bucketize

    structs = _param_structs(traced)
    buckets, _ = _bucketize([(s.shape, s.dtype) for s in structs],
                            int(fusion))
    return len(buckets) * pipeline


def _param_structs(traced: TracedGraph) -> List[jax.ShapeDtypeStruct]:
    leaves = traced.meta.get("param_structs")
    if leaves is None:
        return list(default_param_structs().values())
    return jax.tree_util.tree_leaves(leaves)


def pass_overlap_schedulability(traced: TracedGraph) -> List[Finding]:
    """Two findings, both about what the scheduler is *allowed* to hide:

    * **serialization point** — the config's bucketing plan promises K
      independent compress→exchange chains but the traced graph exposes
      fewer: some bucket's exchange transitively depends on another
      bucket's, so the collectives issue back-to-back and the wire time
      cannot hide under the remaining compute;
    * **measured > statically possible** — when the trace is annotated with
      graft-prof's measured overlap fraction (``meta['measured_overlap']``)
      and it exceeds the dataflow's static upper bound by more than
      :data:`OVERLAP_SLACK`, the measurement is attributing compute time to
      collectives (or vice versa) — the profile pipeline is lying, not the
      scheduler over-performing.
    """
    findings: List[Finding] = []
    g = build_depgraph(traced)
    s = overlap_summary(traced, graph=g)

    expected = _expected_chains(traced)
    if (expected is not None and expected > 1
            and s["exchange_collectives"] >= expected
            and s["independent_chains"] < expected):
        findings.append(Finding(
            pass_name="overlap_schedulability", config=traced.name,
            severity="error", stage=STAGE_EXCHANGE,
            message=(
                f"bucketing promises {expected} independent "
                "compress->exchange chains but the traced graph exposes "
                f"only {s['independent_chains']} "
                f"({s['exchange_collectives']} exchange collectives, the "
                "rest transitively depend on another bucket's exchange) — "
                "a serialization point XLA's latency-hiding scheduler "
                "cannot undo; the buckets' wire time issues back-to-back "
                "instead of overlapping the remaining compute"),
            details=(("expected_chains", int(expected)),
                     ("independent_chains", int(s["independent_chains"])),
                     ("world", traced.world))))

    measured = traced.meta.get("measured_overlap")
    bound = s["static_overlap_bound"]
    if (measured is not None and bound is not None
            and float(measured) > bound + OVERLAP_SLACK):
        findings.append(Finding(
            pass_name="overlap_schedulability", config=traced.name,
            severity="error", stage=STAGE_EXCHANGE,
            message=(
                f"measured overlap fraction {float(measured):.3f} exceeds "
                f"the static upper bound {bound:.3f} (+{OVERLAP_SLACK} "
                "slack) — the dataflow permits at most that much "
                "data-independent compute under the collectives, so the "
                "measured attribution (grace_tpu.profiling overlap "
                "fraction) is misattributing spans, not the scheduler "
                "over-performing; re-check the capture's stage scopes"),
            details=(("measured_overlap", float(measured)),
                     ("static_overlap_bound", round(bound, 6)),
                     ("world", traced.world))))
    return findings


# ---------------------------------------------------------------------------
# pass 6: numeric-range safety
# ---------------------------------------------------------------------------

def _multiplicity_walk(traced: TracedGraph):
    """Forward value-range dataflow: per-var accumulated payload-term
    multiplicity. Seeds every real input at 1 (one rank's payload term),
    constants at 0. Adds/subs sum multiplicities, cross-replica reductions
    multiply by the ranks the collective spans (``axis_index_groups``
    narrows it), ``reduce_sum`` multiplies by the reduced extent (the
    gathered-partials-then-sum shape), everything else takes the max —
    conservative for the linear-accumulation overflow class this pass
    hunts, deliberately blind to multiplicative magnitude growth
    (contractions, scales), which is a different failure mode the guard
    owns at runtime. Returns (worst offender per float dtype, vote psum
    records)."""
    worst: Dict[str, Tuple[int, str]] = {}   # dtype -> (mult, stage)
    votes: List[Tuple[str, str, int]] = []   # (dtype, stage, span)

    def note(eqn, mult):
        for ov in eqn.outvars:
            aval = getattr(ov, "aval", None)
            if aval is None:
                continue
            safe = safe_sum_terms(aval.dtype)
            if safe is not None and mult > safe:
                key = str(aval.dtype)
                if key not in worst or mult > worst[key][0]:
                    worst[key] = (mult, _stage_of(eqn))

    def walk(jaxpr, env):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            ms = [env.get(v, 0) for v in eqn.invars if _is_var(v)]
            m_in = max(ms, default=0)
            if name in ("add", "sub", "add_any"):
                out = sum(ms) if ms else 0
            elif name in _REDUCTIONS and traced.axis_name in _axes_of(eqn):
                span = _group_size(eqn, traced.world)
                out = max(m_in, 1) * span
                if "psum_vote" in _raw_stack(eqn):
                    for v in eqn.invars:
                        if _is_var(v):
                            votes.append((str(v.aval.dtype),
                                          _stage_of(eqn), span))
            elif name == "reduce_sum":
                shape = next((v.aval.shape for v in eqn.invars
                              if _is_var(v)), ())
                axes = eqn.params.get("axes", ())
                factor = int(np.prod([shape[a] for a in axes
                                      if a < len(shape)], dtype=np.int64)) \
                    if axes else 1
                out = m_in * max(factor, 1)
            elif name == "convert_element_type":
                # A cast into a (different) float dtype mints a FRESH
                # payload term: the unit-magnitude budget is a statement
                # about one rank's encoded wire value, so whatever f32
                # arithmetic produced it (batch sums in the backward pass,
                # mean reductions) is the codec's normalization problem,
                # not cross-rank accumulation. Only sums OF the wire dtype
                # — hop adds, psums, gathered-partial reductions — count
                # against the dtype's saturation budget.
                import jax.numpy as jnp
                new_dtype = jnp.dtype(eqn.params.get("new_dtype"))
                out = 1 if jnp.issubdtype(new_dtype, jnp.floating) \
                    else m_in
            elif name in ("dot_general", "conv_general_dilated"):
                # Contractions grow magnitude multiplicatively, not by
                # payload-term accumulation — out of this pass's model.
                out = m_in
            else:
                subs = _sub_jaxprs_of(eqn)
                if subs and name not in COLLECTIVE_PRIMS:
                    branch_outs = []
                    for sub in subs:
                        ops = (eqn.invars[1:] if name == "cond"
                               else eqn.invars)
                        if len(sub.invars) == len(ops):
                            sub_env = {sv: (env.get(ov, 0)
                                            if _is_var(ov) else 0)
                                       for sv, ov in zip(sub.invars, ops)}
                        else:
                            sub_env = {sv: m_in for sv in sub.invars}
                        for cv in getattr(sub, "constvars", ()):
                            sub_env[cv] = 0
                        walk(sub, sub_env)
                        branch_outs.append([
                            sub_env.get(ov, m_in) if _is_var(ov) else 0
                            for ov in sub.outvars])
                    for j, ov in enumerate(eqn.outvars):
                        m = m_in
                        for outs in branch_outs:
                            if len(outs) == len(eqn.outvars):
                                m = max(m, outs[j])
                        env[ov] = m
                    note(eqn, max((max(o, default=0)
                                   for o in branch_outs), default=m_in))
                    continue
                out = m_in
            for ov in eqn.outvars:
                env[ov] = out
            note(eqn, out)

    # Every body input (gradients, state, even hoisted constants) seeds at
    # one payload term — a replicated value is still one magnitude unit,
    # and over-seeding a constant only makes the bound more conservative.
    env = {v: 1 for v in traced.body.invars}
    for v in getattr(traced.body, "constvars", ()):
        env[v] = 0
    walk(traced.body, env)
    return worst, votes


def _codec_payload_structs(traced: TracedGraph):
    """The (n_elems, struct) list the active fusion mode actually hands the
    codec — literally :func:`grace_tpu.transform.fusion_payload_structs`
    (the enumeration the executor and the wire models share), so the
    index-dtype and pack-width checks see the fused leaf sizes, not the
    raw per-parameter ones."""
    return [(n, s) for n, s, _comp in _codec_payload_entries(traced)]


def _rung_compressors(grace) -> List[Any]:
    """Every codec the config can actually run an exchange with: the base
    compressor alone for static configs, or — for a graft-adapt config —
    every non-dense rung of the declared degradation ladder (the base is
    the top rung by :func:`grace_tpu.resilience.adapt.normalize_adapt`'s
    contract; the dense rung 0 is the escape codec, covered by the
    traced-graph analyses directly). This is what "audit every reachable
    ladder rung" means mechanically: the payload-contract checks below
    iterate it."""
    adapt = getattr(grace, "adapt", None)
    ladder = tuple(getattr(adapt, "ladder", ()) or ())
    if not ladder:
        return [getattr(grace, "compressor", None)]
    out: List[Any] = []
    for comp in (getattr(grace, "compressor", None),) + ladder:
        if comp is not None and comp not in out:
            out.append(comp)
    return out


def _codec_payload_entries(traced: TracedGraph):
    """``(n_elems, struct, compressor)`` per compress call: the fusion
    enumeration with the codec that actually encodes each call — for a
    ROUTED config the compressor differs per leaf (the per-leaf route
    table), and for a graft-adapt config EVERY reachable ladder rung
    contributes its own entries, so the index-dtype and pack-width
    contracts are checked against each codec the traced switch can
    dispatch to."""
    from grace_tpu.transform import fusion_payload_structs

    grace = traced.meta.get("grace")
    if getattr(grace, "routes", None):
        from grace_tpu.helper import route_leaves

        named = traced.meta.get("param_structs")
        if named is None:
            from grace_tpu.analysis.trace import default_param_structs
            named = default_param_structs()
        return [(int(np.prod(s.shape, dtype=np.int64)), s, comp)
                for _p, s, comp, _m, _cm in route_leaves(grace, named)]
    structs = _param_structs(traced)
    fusion = getattr(grace, "fusion", None)
    return [(int(np.prod(s.shape, dtype=np.int64)), s, comp)
            for comp in _rung_compressors(grace)
            for s, _count in fusion_payload_structs(structs, fusion)]


def _index_dtype_findings(traced: TracedGraph) -> List[Finding]:
    """Selection-index payloads must be able to address the fused leaf:
    a signed-integer payload array *smaller than the leaf* is an index
    table (Top-K/threshold selections; full-size integer arrays are
    per-element codes and exempt), and its dtype's max must cover
    ``n_elems - 1`` or decode scatters wrap silently."""
    import jax.numpy as jnp

    grace = traced.meta.get("grace")
    if grace is None:
        return []
    findings: List[Finding] = []
    for n_elems, struct, compressor in _codec_payload_entries(traced):
        def encode(x):
            rng = jax.random.key(0)     # shape-only trace
            payload, _, _ = compressor.compress(
                x, compressor.init_state(x), rng)
            return payload

        try:
            payload = jax.eval_shape(encode, struct)
        except Exception:               # e.g. in-compress collectives
            continue
        for leaf in jax.tree_util.tree_leaves(payload):
            dt = jnp.dtype(leaf.dtype)
            if not jnp.issubdtype(dt, jnp.signedinteger):
                continue
            size = int(np.prod(leaf.shape, dtype=np.int64))
            if size >= n_elems:         # per-element codes, not indices
                continue
            if int(jnp.iinfo(dt).max) < n_elems - 1:
                findings.append(Finding(
                    pass_name="numeric_safety", config=traced.name,
                    severity="error", stage="grace/compress",
                    message=(
                        f"{type(compressor).__name__} ships a "
                        f"{dt.name} index payload ({size} entries) for a "
                        f"{n_elems}-element fused leaf, but "
                        f"iinfo({dt.name}).max = {int(jnp.iinfo(dt).max)} "
                        f"< {n_elems - 1} — top positions past the dtype's "
                        "range wrap on decode and scatter into the wrong "
                        "coordinates silently; widen the index dtype or "
                        "shrink the fusion buckets"),
                    details=(("index_dtype", dt.name),
                             ("n_elems", int(n_elems)))))
    return findings


def _packing_findings(traced: TracedGraph, pack_fns=None) -> List[Finding]:
    """Bit-pack width contract: when the codec ships a sub-byte packed
    payload (an unsigned-byte array smaller than the element count), the
    :mod:`grace_tpu.ops.packing` primitives it rides on must round-trip
    their declared widths at boundary sizes and pack into exactly
    ``ceil(n*width/8)`` bytes. ``pack_fns`` injects alternates for the
    seeded-bad tests."""
    import jax.numpy as jnp

    grace = traced.meta.get("grace")
    if grace is None:
        return []
    ships_packed = False
    for n_elems, struct, compressor in _codec_payload_entries(traced):
        def encode(x):
            rng = jax.random.key(0)
            payload, _, _ = compressor.compress(
                x, compressor.init_state(x), rng)
            return payload

        try:
            payload = jax.eval_shape(encode, struct)
        except Exception:
            continue
        for leaf in jax.tree_util.tree_leaves(payload):
            dt = jnp.dtype(leaf.dtype)
            size = int(np.prod(leaf.shape, dtype=np.int64))
            if jnp.issubdtype(dt, jnp.unsignedinteger) \
                    and dt.itemsize == 1 and 0 < size < n_elems:
                ships_packed = True
    if not ships_packed:
        return []
    failures = (_packing_contract(pack_fns) if pack_fns is not None
                else _packing_contract_cached())
    return [Finding(
        pass_name="numeric_safety", config=traced.name, severity="error",
        stage="grace/compress", message=msg) for msg in failures]


@functools.lru_cache(maxsize=1)
def _packing_contract_cached() -> Tuple[str, ...]:
    return _packing_contract(None)


def _packing_contract(pack_fns) -> Tuple[str, ...]:
    import jax.numpy as jnp

    from grace_tpu.ops import packing

    fns = pack_fns or packing.pack_widths()
    out: List[str] = []
    for width, pack, unpack in fns:
        per_byte = 8 // width
        for n in (1, per_byte - 1 or 1, per_byte, per_byte + 1, 64):
            codes = np.full((n,), (1 << width) - 1, np.uint8)
            packed = np.asarray(pack(jnp.asarray(codes)))
            want = -(-n * width // 8)
            if packed.size != want:
                out.append(
                    f"ops/packing: {width}-bit pack of {n} codes produced "
                    f"{packed.size} bytes, expected ceil({n}*{width}/8) = "
                    f"{want} — the wire-size model and every byte-count "
                    "downstream of it are wrong")
                continue
            got = np.asarray(unpack(jnp.asarray(packed), n))
            if not np.array_equal(got.astype(np.uint8), codes):
                out.append(
                    f"ops/packing: {width}-bit round-trip of max code "
                    f"{(1 << width) - 1} over {n} lanes does not "
                    "reconstruct — the declared pack width truncates "
                    "in-range codes (silent payload corruption)")
    return tuple(out)


def _shared_scale_findings(traced: TracedGraph) -> List[Finding]:
    """Shared-scale accumulator overflow, statically: a
    ``payload_algebra='shared_scale'`` codec's integer payload must cover
    ``world · max_level`` or the payload-space hop/psum sums wrap silently
    (integer overflow has no inf for the guard to see). The bound is the
    codec's OWN ``payload_sum_max_world`` — literally the constant the
    communicators' runtime gate raises from — so, like
    ``vote_exact_max_world``, the static pass and the runtime check can
    never disagree. A W=4096 trace of an int8 accumulator fires here; the
    same codec at W=8 is clean."""
    from grace_tpu import comm

    grace = traced.meta.get("grace")
    if grace is None:
        return []
    # Only the payload-summing schedules accumulate W levels in the wire
    # dtype; a gather decodes per rank and never sums payloads.
    if not isinstance(grace.communicator,
                      (comm.Allreduce, comm.RingAllreduce,
                       comm.ReduceScatterAllreduce,
                       comm.HierarchicalAllreduce)):
        return []
    findings: List[Finding] = []
    # Every reachable codec — for a graft-adapt config that is EVERY
    # ladder rung: the controller can dispatch any of them mid-run, so a
    # single rung whose accumulator cannot cover the world is a
    # reachable silent-wrap state, not a hypothetical.
    for comp in _rung_compressors(grace):
        if getattr(comp, "payload_algebra", None) != "shared_scale":
            continue
        bound = comp.payload_sum_max_world()
        if bound is None or traced.world <= bound:
            continue
        findings.append(Finding(
            pass_name="numeric_safety", config=traced.name,
            severity="error", stage=STAGE_EXCHANGE,
            message=(
                f"{type(comp).__name__} payload-space sum spans "
                f"world={traced.world} ranks but its integer accumulator "
                f"carries exact sums only up to world {bound} "
                "(payload_sum_max_world: iinfo(accum_dtype).max // max "
                "level — the same constant the communicators' runtime "
                "gate enforces); beyond it level sums wrap with no "
                "NaN/inf for the guard to catch — widen accum_dtype or "
                "lower quantum_num"),
            details=(("payload_sum_max_world", int(bound)),
                     ("world", traced.world))))
    return findings


def pass_numeric_safety(traced: TracedGraph) -> List[Finding]:
    """Value-range safety of the traced payload arithmetic — the
    silent-saturation class a static pass catches before a chip runs:

    * a float dtype accumulating more unit-magnitude payload terms than
      ``finfo.max / NUMERIC_UNIT_MAG`` permits (hop sums, psums, grouped
      gathers-then-sum) saturates to inf with no NaN for the guard to see
      until downstream arithmetic manufactures one — fp16's 65504 cliff
      falls at W≈256 and every flat psum of fp16 payloads beyond it;
    * vote psums must stay integer-exact: ±1 sums in a dtype with p
      mantissa bits are exact only up to ``2^(p+1)`` ranks
      (:func:`grace_tpu.comm.vote_exact_max_world` — the constant the
      runtime guard reads, re-derived from first principles in the tests);
    * shared-scale integer accumulators must cover ``world · max_level``
      (:func:`_shared_scale_findings` — the homomorphic-payload twin of
      the vote bound, from the codec's own ``payload_sum_max_world``);
    * codec payload contracts: selection-index dtypes vs fused leaf sizes,
      and bit-packing width round-trips (:func:`_packing_findings`).
    """
    from grace_tpu.comm import vote_exact_max_world

    findings: List[Finding] = []
    worst, votes = _multiplicity_walk(traced)
    for dtype, (mult, stage) in sorted(worst.items()):
        safe = safe_sum_terms(dtype)
        findings.append(Finding(
            pass_name="numeric_safety", config=traced.name,
            severity="error", stage=stage,
            message=(
                f"{dtype} accumulation reaches {mult} payload terms at "
                f"world={traced.world} but the dtype saturates at "
                f"~{safe} terms of magnitude {NUMERIC_UNIT_MAG:g} "
                f"(finfo({dtype}).max) — the sum overflows to inf with no "
                "NaN for the guard to catch; accumulate in "
                "float32/bfloat16 and downcast the final result, or cap "
                "the schedule's span"),
            details=(("dtype", dtype), ("terms", int(mult)),
                     ("safe_terms", int(safe)), ("world", traced.world))))
    seen = set()
    for dtype, stage, span in votes:
        bound = vote_exact_max_world(dtype)
        if span > bound and (dtype, span) not in seen:
            seen.add((dtype, span))
            findings.append(Finding(
                pass_name="numeric_safety", config=traced.name,
                severity="error", stage=stage,
                message=(
                    f"majority-vote psum in {dtype} spans {span} ranks but "
                    f"±1 vote sums are integer-exact only up to "
                    f"{bound} (2^(mantissa+1) — "
                    "comm.vote_exact_max_world, the same constant the "
                    "runtime check enforces); beyond it vote tallies "
                    "round and the election silently flips — use "
                    "vote_dtype='float32'"),
                details=(("vote_dtype", dtype), ("span", int(span)),
                         ("exact_max_world", int(bound)))))
    findings.extend(_shared_scale_findings(traced))
    findings.extend(_index_dtype_findings(traced))
    findings.extend(_packing_findings(traced))
    return findings


# ---------------------------------------------------------------------------
# pass 7: HBM footprint
# ---------------------------------------------------------------------------

def footprint_model(grace, params, world: int = 1) -> Dict[str, int]:
    """The config's expected per-rank GraceState bytes scaled to ``world``
    — literally :func:`grace_tpu.profiling.expected_state_footprint`, so
    the static pass and the runtime recorder can never disagree about what
    a config should weigh."""
    from grace_tpu.profiling.recorder import expected_state_footprint

    return expected_state_footprint(grace, params, world=world)


def footprint_report(traced: TracedGraph) -> Dict[str, Any]:
    """Per-rank peak accounting of one traced config: the GraceState rings
    grouped exactly like :func:`grace_tpu.profiling.grace_state_footprint`
    (mem / comp / telem+watch / bookkeeping, from the traced state
    signature's avals) plus the wire buffers the collectives materialize
    (``wire_peak_bytes`` — the largest single collective output per rank,
    e.g. an all_gather's (W, k) stack; ``wire_total_bytes`` — every
    collective output summed, an upper bound when XLA frees eagerly)."""
    mem = comp = telem = book = 0
    for path, aval in traced.state_in:
        head = path.split("/", 1)[0]
        n = _aval_nbytes(aval)
        if head == "mem":
            mem += n
        elif head == "comp":
            comp += n
        elif head in ("telem", "watch"):
            telem += n
        else:
            book += n

    peak = total = n_coll = 0

    def walk(jaxpr):
        nonlocal peak, total, n_coll
        for eqn in jaxpr.eqns:
            if (eqn.primitive.name in COLLECTIVE_PRIMS
                    and traced.axis_name in _axes_of(eqn)):
                n = sum(_aval_nbytes(v.aval) for v in eqn.outvars
                        if hasattr(v, "aval"))
                peak = max(peak, n)
                total += n
                n_coll += 1
            for sub in _sub_jaxprs_of(eqn):
                walk(sub)

    walk(traced.body)
    return {"mem_bytes": mem, "comp_bytes": comp, "telem_bytes": telem,
            "bookkeeping_bytes": book,
            "state_total_bytes": mem + comp + telem + book,
            "wire_peak_bytes": peak, "wire_total_bytes": total,
            "n_collectives": n_coll}


def pass_memory_footprint(traced: TracedGraph) -> List[Finding]:
    """Per-rank HBM accounting findings:

    * **replicated O(W) state** — a replicated (``P()``) state leaf with a
      dimension equal to the world size costs O(W) per rank on every rank
      (O(W²) fleet-wide) and grows every time the job scales — the buffer
      class that should be sharded or windowed instead;
    * **state-model mismatch** — the traced state signature's bytes must
      equal the config's own ``eval_shape(init)`` footprint
      (:func:`grace_tpu.profiling.grace_state_footprint`'s static twin); a
      mismatch means the trace ran under a different codec/fusion/
      telemetry config than the one being audited, the same bug class the
      recorder's live ``grace_state_footprint`` check catches at run time.
    """
    findings: List[Finding] = []
    for path, aval in traced.state_replicated:
        shape = tuple(getattr(aval, "shape", ()))
        if traced.world >= 4 and any(d == traced.world for d in shape):
            findings.append(Finding(
                pass_name="memory_footprint", config=traced.name,
                severity="error",
                message=(
                    f"replicated state leaf '{path}' has shape {shape} "
                    f"with a dimension equal to the world size "
                    f"({traced.world}) — a replicated buffer that scales "
                    "with W costs O(W) HBM per rank on EVERY rank (O(W²) "
                    "fleet-wide) and grows each time the job scales; "
                    "shard it over the axis (partition_specs P(axis)) or "
                    "reduce it to a windowed summary"),
                details=(("path", path), ("shape", tuple(map(int, shape))),
                         ("world", traced.world))))

    grace = traced.meta.get("grace")
    if grace is not None and traced.state_in:
        from grace_tpu.profiling.recorder import grace_state_footprint

        params = traced.meta.get("param_structs")
        if params is None:
            params = default_param_structs()
        try:
            tx = grace.transform(seed=0)
            model = grace_state_footprint(jax.eval_shape(tx.init, params))
        except Exception:
            model = None
        if model is not None:
            rep = footprint_report(traced)
            for key in ("mem_bytes", "comp_bytes", "telem_bytes"):
                if rep[key] != model[key]:
                    findings.append(Finding(
                        pass_name="memory_footprint", config=traced.name,
                        severity="error",
                        message=(
                            f"traced state carries {rep[key]} B of "
                            f"{key.split('_')[0]} state but the config's "
                            f"own eval_shape(init) model says {model[key]} "
                            "B — the trace ran under a different "
                            "codec/fusion/telemetry config than the one "
                            "being audited (the static twin of the "
                            "recorder's grace_state_footprint check)"),
                        details=(("component", key),
                                 ("traced_bytes", int(rep[key])),
                                 ("model_bytes", int(model[key])))))
                    break
    return findings


PASS_FNS = {
    "overlap_schedulability": pass_overlap_schedulability,
    "numeric_safety": pass_numeric_safety,
    "memory_footprint": pass_memory_footprint,
}
