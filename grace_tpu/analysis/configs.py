"""The audited config registry: the enforced codec x communicator matrix.

One entry per *valid* triad the repo supports (the same compatibility
matrix ``Allreduce``/``RingAllreduce``/``TwoShotAllreduce`` enforce at
build time, plus the resilience variants: escape hatch, telemetry,
guard + consensus). ``audit_all`` traces every entry with
:func:`~grace_tpu.analysis.trace.trace_update` (or
:func:`~grace_tpu.analysis.trace.trace_train_step` for ``mode='train'``
entries) and runs the selected passes.

Pass selection per entry:

* ``wire_reconciliation`` runs only on bare-update traces without an
  escape hatch (the escape cond makes "the" wire cost bimodal — telemetry
  prices that flip separately) and without in-compress collectives priced
  analytically at a different granularity;
* train-mode entries (guard/consensus) skip wire reconciliation — the
  audit's fingerprint gathers and the loss pmean are deliberately outside
  the exchange model — but are exactly where ``collective_consistency``
  and ``bit_exactness`` earn their keep.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from grace_tpu.analysis.passes import Finding, PASS_NAMES, run_passes
from grace_tpu.analysis.trace import trace_train_step, trace_update

__all__ = ["AUDIT_CONFIGS", "audit_all", "audit_config", "build_grace",
           "overlap_bound_report"]

_ALL = tuple(PASS_NAMES)
_NO_WIRE = tuple(p for p in PASS_NAMES if p != "wire_reconciliation")


def _cfg(name: str, params: Dict[str, Any], *, passes=_ALL, mode="update",
         guard=None, consensus=None, fsdp=None,
         world=None) -> Dict[str, Any]:
    # world: per-entry audit-mesh override. Most entries trace at the
    # caller's world (8 by default); configs whose payload accumulator
    # legitimately bounds the world — e.g. packed sub-byte homoqsgd,
    # whose payload_sum_max_world is (2^(bits-1)-1)//quantum_num — pin
    # the world their contract actually supports, so the registry stays
    # lint-clean while the out-of-bound worlds remain the rejection
    # demonstrators tests pin explicitly.
    return {"name": name, "params": params, "passes": passes, "mode": mode,
            "guard": guard, "consensus": consensus, "fsdp": fsdp,
            "world": world}


AUDIT_CONFIGS: List[Dict[str, Any]] = [
    # -- linear codecs: the summable-payload Allreduce family ---------------
    _cfg("none-allreduce", {"compressor": "none", "memory": "none",
                            "communicator": "allreduce"}),
    _cfg("fp16-allreduce", {"compressor": "fp16", "memory": "none",
                            "communicator": "allreduce"}),
    _cfg("randomk-allreduce", {"compressor": "randomk",
                               "compress_ratio": 0.5, "memory": "residual",
                               "communicator": "allreduce"}),
    _cfg("powersgd-allreduce", {"compressor": "powersgd",
                                "compress_rank": 2, "memory": "powersgd",
                                "communicator": "allreduce"}),
    # -- the general-purpose allgather family -------------------------------
    _cfg("topk-allgather", {"compressor": "topk", "compress_ratio": 0.3,
                            "memory": "residual",
                            "communicator": "allgather"}),
    _cfg("randomk-allgather", {"compressor": "randomk",
                               "compress_ratio": 0.5, "memory": "residual",
                               "communicator": "allgather"}),
    _cfg("qsgd-allgather", {"compressor": "qsgd", "quantum_num": 64,
                            "use_pallas": False, "memory": "none",
                            "communicator": "allgather"}),
    _cfg("terngrad-allgather", {"compressor": "terngrad", "memory": "none",
                                "communicator": "allgather"}),
    _cfg("signsgd-allgather", {"compressor": "signsgd", "memory": "none",
                               "communicator": "allgather"}),
    _cfg("signum-allgather", {"compressor": "signum", "momentum": 0.9,
                              "memory": "none",
                              "communicator": "allgather"}),
    _cfg("efsignsgd-allgather", {"compressor": "efsignsgd", "lr": 0.1,
                                 "memory": "efsignsgd",
                                 "communicator": "allgather"}),
    _cfg("onebit-allgather", {"compressor": "onebit", "memory": "residual",
                              "communicator": "allgather"}),
    _cfg("natural-allgather", {"compressor": "natural",
                               "memory": "residual",
                               "communicator": "allgather"}),
    _cfg("dgc-allgather", {"compressor": "dgc", "compress_ratio": 0.3,
                           "memory": "dgc", "communicator": "allgather"}),
    _cfg("threshold-allgather", {"compressor": "threshold",
                                 "threshold": 0.01,
                                 "memory": "residual",
                                 "communicator": "allgather"}),
    _cfg("sketch-allgather", {"compressor": "sketch", "quantum_num": 64,
                              "memory": "none",
                              "communicator": "allgather"}),
    _cfg("u8bit-allgather", {"compressor": "u8bit", "memory": "none",
                             "communicator": "allgather"}),
    _cfg("adaq-allgather", {"compressor": "adaq", "compress_ratio": 0.3,
                            "memory": "residual",
                            "communicator": "allgather"}),
    _cfg("inceptionn-allgather", {"compressor": "inceptionn",
                                  "memory": "none",
                                  "communicator": "allgather"}),
    _cfg("topk-broadcast", {"compressor": "topk", "compress_ratio": 0.3,
                            "memory": "residual",
                            "communicator": "broadcast"}),
    # -- vote routing --------------------------------------------------------
    _cfg("signsgd-sign_allreduce", {"compressor": "signsgd",
                                    "memory": "none",
                                    "communicator": "sign_allreduce"}),
    _cfg("signsgd-allreduce-vote", {"compressor": "signsgd",
                                    "memory": "none",
                                    "communicator": "allreduce"}),
    # -- shard-parallel families (flat fusion hands them whole buffers) -----
    _cfg("topk-twoshot", {"compressor": "topk", "compress_ratio": 0.3,
                          "memory": "residual", "communicator": "twoshot",
                          "fusion": "flat"}),
    _cfg("qsgd-twoshot", {"compressor": "qsgd", "quantum_num": 64,
                          "use_pallas": False, "memory": "none",
                          "communicator": "twoshot", "fusion": "flat"}),
    _cfg("topk-ring", {"compressor": "topk", "compress_ratio": 0.3,
                       "memory": "residual", "communicator": "ring",
                       "fusion": "flat"}),
    _cfg("qsgd-ring", {"compressor": "qsgd", "quantum_num": 64,
                       "use_pallas": False, "memory": "none",
                       "communicator": "ring", "fusion": "flat"}),
    _cfg("signsgd-ring", {"compressor": "signsgd", "memory": "none",
                          "communicator": "ring", "fusion": "flat"}),
    _cfg("fp16-ring", {"compressor": "fp16", "memory": "none",
                       "communicator": "ring", "fusion": "flat"}),
    _cfg("randomk-ring", {"compressor": "randomk", "compress_ratio": 0.5,
                          "memory": "residual", "communicator": "ring",
                          "fusion": "flat"}),
    # -- hierarchical ICI×DCN family (ISSUE 7): slice_size=4 puts a slice
    #    boundary inside the 8-way audit mesh (K=2 slices), so the traced
    #    schedule exercises both grouped sub-axis collectives AND the
    #    per-link split reconciliation (wire_reconciliation counts the
    #    intra-slice legs as ICI and the cross-slice gather as DCN against
    #    HierarchicalAllreduce.recv_link_bytes — the mixed split that
    #    makes the xslice projections trustworthy).
    _cfg("topk1pct_hier", {"compressor": "topk", "compress_ratio": 0.01,
                           "topk_algorithm": "chunk", "memory": "residual",
                           "communicator": "hier", "slice_size": 4,
                           "fusion": "flat"}),
    _cfg("qsgd_hier", {"compressor": "qsgd", "quantum_num": 64,
                       "use_pallas": False, "memory": "none",
                       "communicator": "hier", "slice_size": 4,
                       "fusion": "flat"}),
    _cfg("none_hier", {"compressor": "none", "memory": "none",
                       "communicator": "hier", "slice_size": 4,
                       "fusion": "flat"}),
    _cfg("signsgd_hier", {"compressor": "signsgd", "memory": "none",
                          "communicator": "hier", "slice_size": 4,
                          "fusion": "flat"}),
    # -- aggregation-homomorphic family (ISSUE 13): payload-algebra codecs
    #    whose wire payloads SUM on every hop and slice boundary with zero
    #    requant. The homoqsgd traces carry the hoisted shared-scale
    #    negotiation (one pmax before stage 1 — a scalar collective inside
    #    the wire model's atol, audited by wire_reconciliation like every
    #    other traced collective), integer ppermute/gather payloads, and
    #    ONE decode at the schedule's end; numeric_safety additionally
    #    checks the int accumulator against payload_sum_max_world at the
    #    audit world.
    _cfg("homoqsgd-ring", {"compressor": "homoqsgd", "quantum_num": 7,
                           "memory": "residual", "communicator": "ring",
                           "fusion": "flat"}),
    _cfg("homoqsgd-hier", {"compressor": "homoqsgd", "quantum_num": 7,
                           "memory": "residual", "communicator": "hier",
                           "slice_size": 4, "fusion": "flat"}),
    # -- three-tier WAN family (ISSUE 16): slice_size=2 + region_size=4
    #    puts BOTH a slice and a region boundary inside the 8-way audit
    #    mesh (2 regions × 2 slices × 2 ranks), so the traced three-level
    #    schedule exercises intra-slice ppermute hops (ICI), same-region
    #    cross-slice gathers (DCN), and cross-region gathers (WAN) — and
    #    wire_reconciliation reconciles all THREE legs against
    #    HierarchicalAllreduce.recv_link_bytes under the comm's own
    #    (slice_size, region_size).
    _cfg("topk-hier3", {"compressor": "topk", "compress_ratio": 0.25,
                        "topk_algorithm": "chunk", "memory": "residual",
                        "communicator": "hier", "slice_size": 2,
                        "region_size": 4, "fusion": "flat"}),
    # Homomorphic payloads cross the WAN tier exactly-summable (zero
    # requant at BOTH the slice and the region boundary) — the traced
    # schedule is negotiate pmax + int hops + two nested gather-sums +
    # ONE decode.
    _cfg("homoqsgd-hier3", {"compressor": "homoqsgd", "quantum_num": 7,
                            "memory": "residual", "communicator": "hier",
                            "slice_size": 2, "region_size": 4,
                            "fusion": "flat"}),
    # Mergeable count-sketch over the gather family: the sketch algebra's
    # ctx (hash indices/signs) is rng-derived, so the data-free-ctx decode
    # contract holds and the payload (rows × width f32 tables) reconciles
    # against the gather model like any other codec.
    _cfg("countsketch-allgather", {"compressor": "countsketch",
                                   "compress_ratio": 0.25,
                                   "memory": "residual",
                                   "communicator": "allgather"}),
    # -- sharded-model track (ISSUE 14): compressed reduce-scatter on 1-D
    #    and 2-D dp×fsdp meshes. The rscatter schedule is one all_to_all
    #    (the reduce-scatter's data movement) + one all_gather; payload-
    #    space summation for exact/homomorphic codecs, exactly ONE requant
    #    boundary for the rest. The fsdp=2 entries split the 8-way audit
    #    mesh into dp=4 × fsdp=2: the tracer seeds GraceState leaves from
    #    the 2-D partition_specs (P((dp, fsdp))), the replication analysis
    #    runs PER AXIS, and wire_reconciliation counts the dp-axis
    #    collectives at the dp world — proving the whole 7-pass stack
    #    holds on 2-D configs.
    _cfg("topk-rscatter", {"compressor": "topk", "compress_ratio": 0.3,
                           "memory": "residual", "communicator": "rscatter",
                           "fusion": "flat"}),
    _cfg("fp16-rscatter-fsdp", {"compressor": "fp16", "memory": "none",
                                "communicator": "rscatter",
                                "fusion": "flat", "fsdp_axis": "fsdp"},
         fsdp=2),
    _cfg("topk-rscatter-fsdp", {"compressor": "topk",
                                "compress_ratio": 0.3,
                                "memory": "residual",
                                "communicator": "rscatter",
                                "fusion": "flat", "fsdp_axis": "fsdp"},
         fsdp=2),
    _cfg("homoqsgd-rscatter-fsdp", {"compressor": "homoqsgd",
                                    "quantum_num": 7, "memory": "residual",
                                    "communicator": "rscatter",
                                    "fusion": "flat",
                                    "fsdp_axis": "fsdp"}, fsdp=2),
    # ScaleCom-style cyclic Top-K: the rng+step-derived shared index set
    # makes the payload exactly summable, so it rides the psum allreduce
    # at k values/rank with ZERO negotiation bytes (the schedule is
    # rank-deterministic — nothing to broadcast), which this entry pins.
    _cfg("cyclictopk-allreduce", {"compressor": "cyclictopk",
                                  "compress_ratio": 0.3,
                                  "memory": "residual",
                                  "communicator": "allreduce"}),
    # The data-free-ctx unlock (ROADMAP item 4): cyclictopk's ctx is
    # derived from the replicated rng alone, so the hop-pipelined ring
    # rebuilds the scatter map per shard and the exact payload algebra
    # sums losslessly hop by hop.
    _cfg("cyclictopk-ring", {"compressor": "cyclictopk",
                             "compress_ratio": 0.3,
                             "memory": "residual",
                             "communicator": "ring",
                             "fusion": "flat"}),
    # First-class per-leaf codec routing (1-D): the wire model becomes the
    # SUM of per-leaf prices through each leaf's own codec/communicator —
    # wire_reconciliation audits the routed spelling end to end.
    _cfg("routed-topk-fp16", {"compressor": "topk", "compress_ratio": 0.3,
                              "memory": "residual",
                              "communicator": "allgather",
                              "route": [("b", {"compressor": "fp16",
                                               "memory": "none",
                                               "communicator":
                                                   "allreduce"})]}),
    # Routed rscatter over the 2-D mesh: the transformer-track shape —
    # the big leaf rides sparsification through the per-shard
    # reduce-scatter, the small leaf rides dense fp16 psum.
    _cfg("routed-rscatter-fsdp", {"compressor": "topk",
                                  "compress_ratio": 0.3,
                                  "memory": "residual",
                                  "communicator": "rscatter",
                                  "fsdp_axis": "fsdp",
                                  "route": [("b", {"compressor": "fp16",
                                                   "memory": "none",
                                                   "communicator":
                                                       "allreduce"})]},
         fsdp=2),
    # -- degenerate / fusion variants ---------------------------------------
    _cfg("none-identity", {"compressor": "none", "memory": "none",
                           "communicator": "identity"}),
    _cfg("topk-allgather-flat", {"compressor": "topk",
                                 "compress_ratio": 0.3,
                                 "memory": "residual",
                                 "communicator": "allgather",
                                 "fusion": "flat"}),
    _cfg("topk-allgather-grouped", {"compressor": "topk",
                                    "compress_ratio": 0.3,
                                    "memory": "residual",
                                    "communicator": "allgather",
                                    "fusion": "grouped"}),
    # Int-bucket fusion (graft-flow, ISSUE 9): the 1024-byte plan splits
    # the default params into K=2 buckets (w is 1920 B — its own bucket;
    # b rides the second), so the overlap_schedulability pass verifies the
    # traced graph actually exposes 2 independent compress→exchange chains
    # — the schedulability contract the bucketed overlap executor
    # (ISSUE 10) now delivers at runtime.
    _cfg("topk-allgather-bucketed", {"compressor": "topk",
                                     "compress_ratio": 0.3,
                                     "memory": "residual",
                                     "communicator": "allgather",
                                     "fusion": 1024}),
    # -- fused compress-and-pack wire formats (ISSUE 10) --------------------
    # qsgd at quantum_num<=7 ships 4-bit packed nibbles (2 codes/byte):
    # the payload is a sub-byte uint8 array, so numeric_safety's pack-width
    # contract re-verifies ops/packing.pack_4bit on every audit, and
    # wire_reconciliation prices the halved payload against the traced
    # all_gather — the staged path traced here is byte-identical in layout
    # to the fused Pallas kernel (bit-identity pinned in
    # tests/test_pallas_quant.py).
    _cfg("qsgd4-allgather-packed", {"compressor": "qsgd", "quantum_num": 7,
                                    "use_pallas": False, "memory": "none",
                                    "communicator": "allgather"}),
    # Bucketed executor × packed wire × hop-requant ring in one trace: two
    # independent per-bucket ring schedules (14 ppermute hops + 2 gathers),
    # each requantizing 4-bit packed partials — schedulability must count
    # K=2 chains and the wire model must reconcile per-bucket.
    _cfg("qsgd4-ring-packed-bucketed", {"compressor": "qsgd",
                                        "quantum_num": 7,
                                        "use_pallas": False,
                                        "memory": "none",
                                        "communicator": "ring",
                                        "fusion": 1024}),
    # The fused sign-bitpack Pallas kernel traced INSIDE the audited graph
    # (use_pallas=True runs the interpret-mode kernel off-TPU — same
    # pallas_call equation structure as on-chip): proves the kernels are
    # auditable, not a blind spot — the packed payload still reconciles
    # and the pack-width contract still runs.
    _cfg("signsgd-pallas-packed", {"compressor": "signsgd",
                                   "use_pallas": True, "memory": "none",
                                   "communicator": "allgather"}),
    # -- kernel-resident wire path (ISSUE 19) -------------------------------
    # 2-bit packed qsgd (quantum_num=1 → pack_width 2, 4 codes/byte)
    # through the double-buffered ring: pipeline=2 splits the flat buffer
    # into two segments whose full ring schedules trace as independent
    # compress→exchange chains — flow pass 5 counts them via the
    # grace/pipeline/<p> scope tags and requires >= pipeline chains, the
    # static referee for the runtime overlap the wire_pipeline discount
    # prices. Pack-width 2 is re-verified by pass 6's sub-byte audit.
    _cfg("qsgd2-ring-packed-pipelined", {"compressor": "qsgd",
                                         "quantum_num": 1,
                                         "use_pallas": False,
                                         "memory": "none",
                                         "communicator": "ring",
                                         "fusion": "flat", "pipeline": 2}),
    # Packed-wire homomorphic ring with the fused payload accumulate
    # traced INSIDE the audited graph (use_pallas=True → the interpret-
    # mode packed_int_accumulate kernel runs at every hop and the final
    # gather-sum): accum_bits=4 makes the 4-bit two's-complement field
    # BOTH the wire word and the hop accumulator, so
    # payload_sum_max_world tightens to (2^3 - 1)//quantum_num = 7 —
    # this entry audits at world=4 (inside the bound). The 8-way default
    # would fire the static accumulator finding AND the communicators'
    # runtime gate from the same constant, which is exactly the
    # graduated-rejection contract tests/test_wire.py pins at 2 bits.
    _cfg("homoqsgd4-ring-fused", {"compressor": "homoqsgd",
                                  "quantum_num": 1, "accum_bits": 4,
                                  "use_pallas": True, "memory": "residual",
                                  "communicator": "ring",
                                  "fusion": "flat"}, world=4),
    # The fused decode→accumulate boundary kernel inside the two-level
    # schedule: packed 4-bit qsgd through hier's intra-slice hop requants
    # AND the cross-slice boundary, with use_pallas=True swapping the
    # boundary's staged vmap-decompress + aggregate for the fused K-way
    # decode_accumulate pass (wire_fused() live) — the interpret-mode
    # pallas_call equations trace inside the audited graph, proving the
    # kernel-resident boundary is auditable end to end.
    _cfg("hier-fused-boundary", {"compressor": "qsgd", "quantum_num": 7,
                                 "use_pallas": True, "memory": "none",
                                 "communicator": "hier", "slice_size": 4,
                                 "fusion": "flat"}),
    # The fused-boundary schedule's train-mode twin under the full
    # resilience stack: the same packed qsgd + interpret-mode wire
    # kernels, now inside the guarded train step with the consensus audit
    # fingerprinting downstream — the pallas_call equations sit inside
    # the escape cond's compressed branch, and collective_consistency /
    # bit_exactness must bless the kernel-resident path exactly as they
    # bless the staged one.
    _cfg("hier-fused-boundary-guard-consensus",
         {"compressor": "qsgd", "quantum_num": 7, "use_pallas": True,
          "memory": "none", "communicator": "hier", "slice_size": 4,
          "fusion": "flat", "escape": "fp16", "consensus": True},
         passes=_NO_WIRE, mode="train",
         guard={"fallback_after": 3, "fallback_steps": 8}, consensus=True),
    # -- graft-watch variants (ISSUE 8): the watch summary adds a lax.cond
    #    (window-boundary predicate from the replicated step counter) whose
    #    taken branch issues an all_gather the untaken branch lacks — the
    #    exact branch-divergent-collective shape pass 1 condemns when the
    #    predicate is rank-varying, so these entries are the standing proof
    #    it blesses the legal version. The non-escape entries keep
    #    wire_reconciliation: the gather's (W-1)·12 B sit inside the
    #    documented atol, pinning that the watch cost stays "tiny" — a
    #    watch redesign that starts gathering big vectors every window
    #    becomes a lint error, not a silent telemetry tax.
    _cfg("topk-watch", {"compressor": "topk", "compress_ratio": 0.3,
                        "memory": "residual", "communicator": "allgather",
                        "telemetry": True, "watch": 5}),
    _cfg("qsgd-ring-watch", {"compressor": "qsgd", "quantum_num": 64,
                             "use_pallas": False, "memory": "none",
                             "communicator": "ring", "fusion": "flat",
                             "telemetry": True, "watch": 5}),
    _cfg("hier-watch", {"compressor": "topk", "compress_ratio": 0.01,
                        "topk_algorithm": "chunk", "memory": "residual",
                        "communicator": "hier", "slice_size": 4,
                        "fusion": "flat", "telemetry": True, "watch": 5}),
    # -- graft-adapt variants (ISSUE 15): the in-graph adaptive controller
    #    — a lax.switch over the WHOLE degradation ladder (branch 0 the
    #    dense escape psum, branch r the rung-r codec's full schedule)
    #    whose index derives from replicated policy state + the replicated
    #    fallback flag, plus the per-step scalar pmean/pmax signal
    #    reductions. These entries are the standing proof pass 1 blesses
    #    the legal version of EXACTLY the shape it exists to condemn
    #    (branch-divergent collective sequences under a predicate), and
    #    flow pass 6 audits every reachable rung's payload contract —
    #    including each shared-scale rung's payload_sum_max_world bound.
    #    Wire reconciliation is excluded like every escape-carrying entry:
    #    the ladder makes "the" wire cost R-modal by design (telemetry
    #    prices the flip per rung instead).
    _cfg("adapt-homoqsgd-ring",
         {"compressor": "homoqsgd", "quantum_num": 7, "memory": "residual",
          "communicator": "ring", "fusion": "flat", "escape": "fp16",
          "telemetry": True,
          "adapt": {"window": 5, "ladder": [{"quantum_num": 127}]}},
         passes=_NO_WIRE),
    _cfg("adapt-topk-hier",
         {"compressor": "topk", "compress_ratio": 0.01,
          "topk_algorithm": "chunk", "memory": "residual",
          "communicator": "hier", "slice_size": 4, "fusion": "flat",
          "escape": "fp16", "telemetry": True,
          "adapt": {"window": 5, "ladder": [{"compress_ratio": 0.04}]}},
         passes=_NO_WIRE),
    # The controller under the full resilience stack: the guard's psum-OR
    # feeds the fallback flag that forces rung 0, the consensus audit
    # fingerprints (and would repair) the replicated AdaptState, and the
    # ladder switch nests inside the guarded train step — every
    # replicated-predicate argument graft-adapt makes, verified in one
    # trace.
    _cfg("adapt-guard-consensus",
         {"compressor": "topk", "compress_ratio": 0.05,
          "memory": "residual", "communicator": "allgather",
          "escape": "fp16", "telemetry": True, "consensus": True,
          "adapt": {"window": 5, "ladder": [{"compress_ratio": 0.2}]}},
         passes=_NO_WIRE, mode="train",
         guard={"fallback_after": 3, "fallback_steps": 8}, consensus=True),
    # -- graft-retune variants (ISSUE 18): the two configs the online
    #    re-tuner promotes between. The PowerSGD rank ladder is the
    #    rung-invariant layout's standing proof: every rung's Q/P state is
    #    padded to the ladder max rank so ONE lax.switch dispatches all
    #    rungs over one state shape — a rank move is a mask flip, never a
    #    reshape, which is what makes mid-run promotion (and the adapt
    #    controller's tighten/loosen) a pure index change the auditor can
    #    trace. This entry is also what the retune PREPARE gate audits
    #    before staging a powersgd+ladder candidate.
    _cfg("adapt-powersgd-rankladder",
         {"compressor": "powersgd", "compress_rank": 4,
          "memory": "powersgd", "communicator": "allreduce",
          "escape": "fp16", "telemetry": True,
          "adapt": {"window": 5, "ladder": [{"compress_rank": 1}]}},
         passes=_NO_WIRE),
    # The retune drill's incumbent under the full resilience stack: the
    # shared-scale homomorphic codec inside the guarded train step with
    # the consensus audit fingerprinting its replicated state — the exact
    # config the controller checkpoints as last-known-good and demotes
    # back to, so its audited trace is the standing proof the demotion
    # target itself lints clean.
    _cfg("retune-incumbent-homoqsgd",
         {"compressor": "homoqsgd", "quantum_num": 7, "memory": "residual",
          "communicator": "allreduce", "fusion": "flat", "escape": "fp16",
          "telemetry": True, "consensus": True},
         passes=_NO_WIRE, mode="train",
         guard={"fallback_after": 3, "fallback_steps": 8}, consensus=True),
    # -- resilience variants: the conds the auditor exists for --------------
    _cfg("topk-escape-telemetry",
         {"compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
          "communicator": "allgather", "escape": "fp16", "telemetry": True},
         passes=_NO_WIRE),
    _cfg("topk-guard-consensus",
         {"compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
          "communicator": "allgather", "escape": "fp16", "telemetry": True,
          "consensus": True},
         passes=_NO_WIRE, mode="train",
         guard={"fallback_after": 3, "fallback_steps": 8}, consensus=True),
    _cfg("ring-guard-consensus",
         {"compressor": "qsgd", "quantum_num": 64, "use_pallas": False,
          "memory": "none", "communicator": "ring", "fusion": "flat",
          "escape": "fp16", "consensus": True},
         passes=_NO_WIRE, mode="train",
         guard={"fallback_after": 3, "fallback_steps": 8}, consensus=True),
    # The nested-axis schedule under the full resilience stack: the escape
    # cond's branches now differ by grouped sub-axis collectives, and the
    # consensus audit's fingerprint gathers run downstream of a
    # hierarchically-aggregated update — collective_consistency must bless
    # both (replicated predicates) with the two-level exchange in place.
    _cfg("hier-guard-consensus",
         {"compressor": "topk", "compress_ratio": 0.01,
          "topk_algorithm": "chunk", "memory": "residual",
          "communicator": "hier", "slice_size": 4, "fusion": "flat",
          "escape": "fp16", "consensus": True},
         passes=_NO_WIRE, mode="train",
         guard={"fallback_after": 3, "fallback_steps": 8}, consensus=True),
    # The bucketed executor under the full resilience stack (ISSUE 10):
    # the escape cond's compressed branch is now K=2 per-bucket pipelines
    # (its dense branch stays per-leaf — branches differ by whole
    # schedules, legal only because the fallback predicate is replicated),
    # the guard's post-exchange check runs once over ALL buckets' updates
    # and its rollback selects the whole per-bucket state tuple
    # atomically, and the consensus audit fingerprints downstream of the
    # split — collective_consistency and bit_exactness must bless all of
    # it with the bucketed schedule in place.
    _cfg("bucketed-guard-consensus",
         {"compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
          "communicator": "allgather", "fusion": 1024, "escape": "fp16",
          "telemetry": True, "consensus": True},
         passes=_NO_WIRE, mode="train",
         guard={"fallback_after": 3, "fallback_steps": 8}, consensus=True),
    # The homomorphic two-level schedule under the full resilience stack
    # (ISSUE 13): the escape cond's compressed branch is now the hier
    # payload-space integer summation (negotiate pmax + int ppermute hops
    # + int cross-slice gather-sum + ONE decode) while its dense branch
    # stays the fp16 psum — branches differ by whole schedules, legal only
    # because the fallback predicate is replicated; the consensus audit
    # fingerprints downstream of the homomorphic aggregate, so
    # collective_consistency and bit_exactness must bless the zero-requant
    # path end to end.
    _cfg("homoqsgd-hier-guard-consensus",
         {"compressor": "homoqsgd", "quantum_num": 7, "memory": "residual",
          "communicator": "hier", "slice_size": 4, "fusion": "flat",
          "escape": "fp16", "consensus": True},
         passes=_NO_WIRE, mode="train",
         guard={"fallback_after": 3, "fallback_steps": 8}, consensus=True),
    # The three-level WAN schedule under the full resilience stack
    # (ISSUE 16): the escape cond's compressed branch now carries THREE
    # nested levels of grouped sub-axis collectives (intra-slice hops,
    # same-region cross-slice gather, cross-region gather) plus the
    # slice- and region-boundary requants, while its dense branch stays
    # the fp16 psum; the consensus audit fingerprints downstream of the
    # three-level aggregate — collective_consistency and bit_exactness
    # must bless every replicated-predicate argument with both extra
    # boundaries in place.
    _cfg("hier3-guard-consensus",
         {"compressor": "topk", "compress_ratio": 0.25,
          "topk_algorithm": "chunk", "memory": "residual",
          "communicator": "hier", "slice_size": 2, "region_size": 4,
          "fusion": "flat", "escape": "fp16", "consensus": True},
         passes=_NO_WIRE, mode="train",
         guard={"fallback_after": 3, "fallback_steps": 8}, consensus=True),
    # The full observability+resilience stack in one trace: watch's gated
    # gather, the escape cond, the guard's psum-OR and the consensus audit
    # all nested in one train step — every replicated-predicate argument
    # the system makes, verified together.
    _cfg("watch-guard-consensus",
         {"compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
          "communicator": "allgather", "escape": "fp16", "telemetry": True,
          "watch": 5, "consensus": True},
         passes=_NO_WIRE, mode="train",
         guard={"fallback_after": 3, "fallback_steps": 8}, consensus=True),
    # The sharded-model resilience stack in one 2-D trace (ISSUE 14): a
    # ROUTED rscatter exchange (per-leaf codecs, per-shard reduce-scatter
    # over dp) under guard + consensus on the dp×fsdp mesh. The escape
    # cond's branches differ by whole routed schedules, the guard's
    # psum-OR and the consensus audit's fingerprint gathers all run over
    # the dp axis only — collective_consistency must bless every
    # replicated-predicate argument with the 2-D seeding in place
    # (fingerprints match replicas per fsdp shard by construction).
    _cfg("rscatter-fsdp-routed-guard-consensus",
         {"compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
          "communicator": "rscatter", "fsdp_axis": "fsdp",
          "route": [("b", {"compressor": "fp16", "memory": "none",
                           "communicator": "allreduce"})],
          "escape": "fp16", "consensus": True},
         passes=_NO_WIRE, mode="train", fsdp=2,
         guard={"fallback_after": 3, "fallback_steps": 8}, consensus=True),
]

# -- tuner-generated variants (ISSUE 12) -----------------------------------
# graft-tune's candidate generator crosses codec/communicator/fusion knobs
# the hand-written registry left uncovered (bucketed executor OVER the
# two-level hier schedule; packed 4-bit wire through hier's hop AND
# slice-boundary requant points). Registering them here means
# `graft_lint --all-configs` audits everything the tuner can emit — the
# tuner consumes this registry, so a variant it may shortlist is never a
# lint blind spot. Entries live in grace_tpu.tuning.candidates (lazy
# analysis imports there keep this append cycle-free).
from grace_tpu.tuning.candidates import variant_audit_entries  # noqa: E402

AUDIT_CONFIGS.extend(
    _cfg(name, params) for name, params, _why in variant_audit_entries())


def build_grace(entry: Dict[str, Any]):
    """The Grace bundle for one registry entry."""
    from grace_tpu.helper import grace_from_params
    return grace_from_params(dict(entry["params"]))


def overlap_bound_report(entry: Dict[str, Any], *, world: int = 8
                         ) -> Optional[Dict[str, Any]]:
    """Schedulability evidence for one bucketed (``fusion=<int bytes>``)
    update-mode registry entry: the static overlap upper bound, the counted
    independent compress→exchange chains, and the bucketing plan's promised
    K. ``None`` for entries the overlap sandwich doesn't apply to (non-int
    fusion, or train mode — the fwd/bwd graph drowns the bound in model
    compute). Written into ``LINT_LAST.json`` by ``tools/graft_lint.py
    --all-configs`` so the measured side of the sandwich
    (``tools/perf_report.py --overlap-config``) always has the static side
    on record next to the lint verdict it came from."""
    from grace_tpu.analysis import flow

    fusion = entry["params"].get("fusion")
    if entry.get("mode", "update") != "update" \
            or isinstance(fusion, bool) or not isinstance(fusion, int):
        return None
    world = int(entry.get("world") or world)
    grace = entry.get("grace") or build_grace(entry)
    traced = trace_update(grace, world=world, name=entry["name"],
                          meta={"grace": grace})
    s = flow.overlap_summary(traced)
    bound = s["static_overlap_bound"]
    return {"static_overlap_bound": (round(bound, 6)
                                     if bound is not None else None),
            "independent_chains": int(s["independent_chains"]),
            "expected_chains": flow._expected_chains(traced),
            "exchange_collectives": int(s["exchange_collectives"]),
            "world": int(world)}


def audit_config(entry: Dict[str, Any], *, world: int = 8
                 ) -> List[Finding]:
    """Trace one registry entry (or an ad-hoc ``{'name', 'params', ...}``
    dict) and run its passes. Trace failures surface as findings, not
    exceptions — a config that stops tracing at all is itself a finding."""
    name = entry["name"]
    passes = tuple(entry.get("passes") or PASS_NAMES)
    world = int(entry.get("world") or world)
    grace = entry.get("grace") or build_grace(entry)
    meta = {"grace": grace, "params": entry.get("params")}
    try:
        if entry.get("mode", "update") == "train":
            traced = trace_train_step(
                grace, world=world, guard=entry.get("guard"),
                consensus=entry.get("consensus"), name=name, meta=meta,
                fsdp=entry.get("fsdp"))
        else:
            traced = trace_update(grace, world=world, name=name, meta=meta,
                                  fsdp=entry.get("fsdp"))
    except Exception as e:                               # noqa: BLE001
        return [Finding(
            pass_name="trace", config=name, severity="error",
            message=(f"config failed to trace on the abstract mesh: "
                     f"{type(e).__name__}: {e} — if this is a "
                     "ConcretizationTypeError, a traced value is forcing a "
                     "host sync (Python control flow / float() on a "
                     "tracer), the exact retrace hazard pass 4 hunts"))]
    return run_passes(traced, passes)


def audit_all(configs: Optional[Sequence[Dict[str, Any]]] = None, *,
              world: int = 8, progress=None) -> List[Finding]:
    """Audit every registry config; returns the concatenated findings."""
    findings: List[Finding] = []
    for entry in (configs if configs is not None else AUDIT_CONFIGS):
        if progress is not None:
            progress(entry["name"])
        findings.extend(audit_config(entry, world=world))
    return findings
