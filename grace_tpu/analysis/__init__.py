"""graft-lint: static SPMD auditing of the compressed-exchange pipeline.

PRs 1-4 grew three hazard classes that only manifest at multi-chip runtime:
collectives inside ``lax.cond`` branches (the guard/consensus/dense-escape
conds) that can deadlock or desync ranks if branch structure diverges on a
rank-varying predicate; bit-pattern data flowing through float-space
reductions (the ``-0.0 + 0.0`` aliasing bug the consensus repair path fixed
by hand in PR 3); and a hand-maintained ``Communicator.recv_wire_bytes``
model that telemetry and bench both trust but nothing verified against the
actual traced graph. EQuARX (PAPERS.md) shows quantized-collective
correctness lives or dies on the XLA-level structure of the collective, and
THC's homomorphic-compression argument is exactly a property that can be
checked statically — so catch these at trace time on a CPU in CI, not at
step 40k on a v4 pod.

The auditor traces any registered codec x communicator x resilience config
to a jaxpr with **no devices** (``AbstractMesh`` + ``shard_map``, see
:mod:`.trace`) and walks it with composable passes (:mod:`.passes`):

* ``collective_consistency`` — branch-divergent collective sequences under
  a ``lax.cond``/``lax.while_loop`` whose predicate is not provably
  replicated (cross-rank deadlock/desync);
* ``bit_exactness`` — bit-pattern data (``bitcast_convert_type`` products:
  fingerprints, checksums, masked-broadcast words) reaching a float-space
  cross-replica reduction (the PR-3 ±0.0 aliasing bug class);
* ``wire_reconciliation`` — per-rank received collective bytes counted
  from the jaxpr vs the ``Communicator.recv_wire_bytes`` model, within the
  tolerance documented in :mod:`grace_tpu.core`;
* ``signature_stability`` — abstract state signature must be a fixed point
  of ``update`` (weak-type promotions / Python-scalar closure leaks force a
  retrace every step), and no host callbacks inside the compiled step.

:mod:`.flow` (graft-flow, ISSUE 9) adds the dependence-graph layer — an
equation-level DAG with ancestor closure and gradient-root tracking — and
three passes on it: ``overlap_schedulability`` (static upper bound on the
overlap fraction graft-prof measures + independent compress→exchange chain
counting, condemning serialization points that defeat ``fusion=<bytes>``
bucketing), ``numeric_safety`` (value-range abstract interpretation over
payload dtypes: fp16 accumulation overflow at large W, vote-sum
integer-exactness against :func:`grace_tpu.comm.vote_exact_max_world`,
selection-index dtype and bit-pack width contracts), and
``memory_footprint`` (eval_shape per-rank GraceState + wire-buffer
accounting, the static twin of
:func:`grace_tpu.profiling.grace_state_footprint`, flagging replicated
O(W) buffers).

:mod:`.rules` adds an AST-level repo rule engine (compressor capability
declarations, telemetry FIELDS reducers, pytest marker registration);
``tools/graft_lint.py`` is the CLI; ``tests/test_analysis.py`` and
``tests/test_flow.py`` are the CI gate, including deliberately seeded bad
graphs proving each pass fires.
"""

from grace_tpu.analysis.trace import (TracedGraph, abstract_mesh, trace_fn,
                                      trace_train_step, trace_update)
from grace_tpu.analysis.passes import (Finding, PASS_NAMES,
                                       pass_bit_exactness,
                                       pass_collective_consistency,
                                       pass_signature_stability,
                                       pass_wire_reconciliation, run_passes)
from grace_tpu.analysis.flow import (DepGraph, DepNode, build_depgraph,
                                     footprint_model, footprint_report,
                                     overlap_summary,
                                     pass_memory_footprint,
                                     pass_numeric_safety,
                                     pass_overlap_schedulability)
from grace_tpu.analysis.configs import (AUDIT_CONFIGS, audit_all,
                                        audit_config, build_grace,
                                        overlap_bound_report)
from grace_tpu.analysis.rules import RULE_NAMES, run_repo_rules
from grace_tpu.analysis.report import (findings_to_json, render_text,
                                       write_jsonl)

__all__ = [
    "TracedGraph", "abstract_mesh", "trace_fn", "trace_update",
    "trace_train_step",
    "Finding", "PASS_NAMES", "run_passes",
    "pass_collective_consistency", "pass_bit_exactness",
    "pass_wire_reconciliation", "pass_signature_stability",
    "DepGraph", "DepNode", "build_depgraph", "overlap_summary",
    "footprint_model", "footprint_report",
    "pass_overlap_schedulability", "pass_numeric_safety",
    "pass_memory_footprint",
    "AUDIT_CONFIGS", "audit_all", "audit_config", "build_grace",
    "overlap_bound_report",
    "RULE_NAMES", "run_repo_rules",
    "findings_to_json", "render_text", "write_jsonl",
]
