"""AST-level repo rules: invariants the type system can't hold for us.

Unlike the jaxpr passes (which audit *traced behavior*), these rules audit
*source*: contracts every new contribution must state explicitly, enforced
forever instead of living in review-comment folklore.

* ``compressor-capabilities`` — every ``Compressor`` subclass must declare
  ``payload_algebra`` and ``supports_hop_requant`` in its own class body.
  These two declarations are the communicator compatibility matrix
  (``Allreduce``/``RingAllreduce``/``HierarchicalAllreduce`` dispatch
  their accumulation path on the algebra; ``summable_payload`` is now a
  property DERIVED from it, so declaring the algebra is the one signed
  statement); an inherited implicit ``None`` is *probably* right but
  silently wrong for a new linear/homomorphic codec, and the declaration
  is the author's signed statement either way.
* ``telemetry-fields-reducer`` — every ``FIELDS`` entry in
  ``telemetry/state.py`` must name a host-side reducer from the known set;
  the reader aggregates flush bundles by that string and an unknown one
  becomes a silent mis-aggregation.
* ``pytest-marker-registration`` — every ``pytest.mark.<name>`` used under
  ``tests/``/``tools/`` must be registered in ``pyproject.toml`` (pytest
  only warns on unknown markers, so a typo'd marker silently drops tests
  from ``-m`` selections).
* ``grace-state-field-roles`` — every field in the ``GraceState`` class
  body must appear in exactly one of ``GRACE_VARYING_FIELDS`` /
  ``GRACE_REPLICATED_FIELDS``. Those constants drive ``partition_specs``,
  elastic world-resize carry, the guard's rollback contract, and the
  replication-contract lint pass; a field in neither silently gets no
  layout and no audit. The rule catches the drift at the AST before the
  new field is ever traced.

``run_repo_rules(sources=...)`` accepts an in-memory ``{relpath: source}``
override so the seeded-bad-source tests can prove each rule fires without
touching the working tree.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional

from grace_tpu.analysis.passes import Finding

__all__ = ["RULE_NAMES", "run_repo_rules", "repo_root",
           "registered_markers"]

RULE_NAMES = ("compressor-capabilities", "telemetry-fields-reducer",
              "pytest-marker-registration", "grace-state-field-roles")

_REQUIRED_CAPS = ("payload_algebra", "supports_hop_requant")
_KNOWN_REDUCERS = {"first", "mean", "max", "min", "sum"}
# Markers pytest ships (or plugins this repo uses) — never need registering.
_BUILTIN_MARKS = {"parametrize", "skip", "skipif", "xfail", "usefixtures",
                  "filterwarnings", "timeout", "tryfirst", "trylast",
                  "no_cover", "anyio", "asyncio"}


def repo_root() -> str:
    """The repo checkout: parent of the installed grace_tpu package."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(here)


def _read(root: str, rel: str,
          sources: Optional[Dict[str, str]]) -> Optional[str]:
    if sources is not None and rel in sources:
        return sources[rel]
    path = os.path.join(root, rel)
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        return None


def _iter_py(root: str, reldir: str,
             sources: Optional[Dict[str, str]]) -> List[str]:
    """Relative paths of .py files under ``reldir`` (plus any in-memory
    overrides living there)."""
    rels = []
    absdir = os.path.join(root, reldir)
    if os.path.isdir(absdir):
        for dirpath, _dirs, files in os.walk(absdir):
            for fn in sorted(files):
                if fn.endswith(".py"):
                    rels.append(os.path.relpath(os.path.join(dirpath, fn),
                                                root))
    if sources is not None:
        for rel in sources:
            if rel.startswith(reldir) and rel.endswith(".py") \
                    and rel not in rels:
                rels.append(rel)
    return rels


def _class_assigns(cls: ast.ClassDef) -> set:
    names = set()
    for node in cls.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                            ast.Name):
            names.add(node.target.id)
    return names


def _base_names(cls: ast.ClassDef) -> List[str]:
    out = []
    for b in cls.bases:
        if isinstance(b, ast.Name):
            out.append(b.id)
        elif isinstance(b, ast.Attribute):
            out.append(b.attr)
    return out


def rule_compressor_capabilities(root: str, sources=None) -> List[Finding]:
    findings: List[Finding] = []
    for rel in _iter_py(root, os.path.join("grace_tpu", "compressors"),
                        sources):
        src = _read(root, rel, sources)
        if src is None:
            continue
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            findings.append(Finding(
                pass_name="compressor-capabilities", config=rel,
                severity="error", message=f"unparseable source: {e}"))
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if not any(b.endswith("Compressor") for b in _base_names(node)):
                continue
            missing = [c for c in _REQUIRED_CAPS
                       if c not in _class_assigns(node)]
            if missing:
                findings.append(Finding(
                    pass_name="compressor-capabilities",
                    config=f"{rel}:{node.lineno}", severity="error",
                    message=(
                        f"{node.name} does not declare "
                        f"{'/'.join(missing)} in its class body — these "
                        "declarations ARE the communicator compatibility "
                        "matrix (payload_algebra selects the payload-space "
                        "accumulation path: exact/shared_scale/sketch/"
                        "None, from which summable_payload derives; "
                        "supports_hop_requant opts into RingAllreduce "
                        "per-hop requantization); state them explicitly "
                        "even when None/False so the contract is visible "
                        "at the definition site"),
                    details=(("class", node.name),)))
    return findings


def rule_telemetry_fields(root: str, sources=None) -> List[Finding]:
    rel = os.path.join("grace_tpu", "telemetry", "state.py")
    src = _read(root, rel, sources)
    if src is None:
        return [Finding(pass_name="telemetry-fields-reducer", config=rel,
                        severity="error", message="state.py not found")]
    findings: List[Finding] = []
    tree = ast.parse(src)
    fields_node = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "FIELDS":
                    fields_node = node.value
    if fields_node is None or not isinstance(fields_node,
                                             (ast.Tuple, ast.List)):
        return [Finding(pass_name="telemetry-fields-reducer", config=rel,
                        severity="error",
                        message="FIELDS tuple literal not found")]
    for i, elt in enumerate(fields_node.elts):
        ok = (isinstance(elt, ast.Tuple) and len(elt.elts) == 2
              and all(isinstance(e, ast.Constant)
                      and isinstance(e.value, str) for e in elt.elts))
        if not ok:
            findings.append(Finding(
                pass_name="telemetry-fields-reducer",
                config=f"{rel}:{elt.lineno}", severity="error",
                message=(f"FIELDS[{i}] is not a (name, reducer) string "
                         "pair — the reader aggregates flush bundles by "
                         "the reducer string")))
            continue
        name, reducer = (e.value for e in elt.elts)
        if reducer not in _KNOWN_REDUCERS:
            findings.append(Finding(
                pass_name="telemetry-fields-reducer",
                config=f"{rel}:{elt.lineno}", severity="error",
                message=(f"FIELDS entry {name!r} names unknown reducer "
                         f"{reducer!r} (known: "
                         f"{sorted(_KNOWN_REDUCERS)}) — the host-side "
                         "cross-rank aggregation would silently fall "
                         "through")))
    return findings


def registered_markers(root: str, sources=None) -> set:
    """Marker names registered in pyproject.toml (minimal TOML-free parse:
    the quoted strings of the ``markers = [...]`` array, first word before
    the colon)."""
    src = _read(root, "pyproject.toml", sources)
    if src is None:
        return set()
    m = re.search(r"markers\s*=\s*\[(.*?)\]", src, re.DOTALL)
    if not m:
        return set()
    names = set()
    for entry in re.findall(r"[\"']([^\"']+)[\"']", m.group(1)):
        names.add(entry.split(":")[0].strip())
    return names


def rule_pytest_markers(root: str, sources=None) -> List[Finding]:
    registered = registered_markers(root, sources) | _BUILTIN_MARKS
    findings: List[Finding] = []
    for reldir in ("tests", "tools"):
        for rel in _iter_py(root, reldir, sources):
            src = _read(root, rel, sources)
            if src is None:
                continue
            try:
                tree = ast.parse(src)
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                # pytest.mark.<name> — attribute chain rooted at pytest.
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Attribute)
                        and node.value.attr == "mark"
                        and isinstance(node.value.value, ast.Name)
                        and node.value.value.id == "pytest"):
                    name = node.attr
                    if name not in registered:
                        findings.append(Finding(
                            pass_name="pytest-marker-registration",
                            config=f"{rel}:{node.lineno}",
                            severity="error",
                            message=(
                                f"pytest marker {name!r} is not "
                                "registered in pyproject.toml "
                                "[tool.pytest.ini_options] markers — "
                                "pytest only warns on unknown markers, so "
                                f"'-m {name}' selections silently go "
                                "empty on a typo"),
                            details=(("marker", name),)))
    return findings


def _tuple_literal(tree: ast.Module, name: str) -> Optional[set]:
    """The string elements of a module-level ``name = ("a", "b", ...)``
    assignment, or None when absent/not a literal."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name \
                        and isinstance(node.value, (ast.Tuple, ast.List)):
                    elts = node.value.elts
                    if all(isinstance(e, ast.Constant)
                           and isinstance(e.value, str) for e in elts):
                        return {e.value for e in elts}
    return None


def rule_grace_state_field_roles(root: str, sources=None) -> List[Finding]:
    rel = os.path.join("grace_tpu", "transform.py")
    src = _read(root, rel, sources)
    if src is None:
        return [Finding(pass_name="grace-state-field-roles", config=rel,
                        severity="error", message="transform.py not found")]
    tree = ast.parse(src)
    cls = next((n for n in ast.walk(tree)
                if isinstance(n, ast.ClassDef) and n.name == "GraceState"),
               None)
    if cls is None:
        return [Finding(pass_name="grace-state-field-roles", config=rel,
                        severity="error",
                        message="GraceState class not found")]
    varying = _tuple_literal(tree, "GRACE_VARYING_FIELDS")
    replicated = _tuple_literal(tree, "GRACE_REPLICATED_FIELDS")
    findings: List[Finding] = []
    if varying is None or replicated is None:
        missing = [n for n, v in (("GRACE_VARYING_FIELDS", varying),
                                  ("GRACE_REPLICATED_FIELDS", replicated))
                   if v is None]
        return [Finding(
            pass_name="grace-state-field-roles", config=rel,
            severity="error",
            message=(f"{'/'.join(missing)} string-tuple literal not found "
                     "in transform.py — the field-role constants must "
                     "stay statically readable"))]
    # Field names come from the class body's annotated assignments, so a
    # freshly added field is caught before it is ever traced.
    fields = [n.target.id for n in cls.body
              if isinstance(n, ast.AnnAssign)
              and isinstance(n.target, ast.Name)]
    for f in fields:
        if f not in varying and f not in replicated:
            findings.append(Finding(
                pass_name="grace-state-field-roles",
                config=f"{rel}:{cls.lineno}", severity="error",
                message=(
                    f"GraceState field {f!r} appears in neither "
                    "GRACE_VARYING_FIELDS nor GRACE_REPLICATED_FIELDS — "
                    "add it to GRACE_VARYING_FIELDS (per-rank data, "
                    "sharded by partition_specs, re-initialized on "
                    "elastic resize) or GRACE_REPLICATED_FIELDS "
                    "(bit-identical across ranks, carried through "
                    "resize); without a role the field gets no layout, "
                    "no rollback audit, and no replication check"),
                details=(("field", f),)))
        if f in varying and f in replicated:
            findings.append(Finding(
                pass_name="grace-state-field-roles",
                config=f"{rel}:{cls.lineno}", severity="error",
                message=(f"GraceState field {f!r} appears in BOTH "
                         "field-role constants — the roles are exclusive"),
                details=(("field", f),)))
    for f in sorted((varying | replicated) - set(fields)):
        findings.append(Finding(
            pass_name="grace-state-field-roles", config=rel,
            severity="error",
            message=(f"field-role constants name {f!r}, which is not a "
                     "GraceState field — stale entry after a rename?"),
            details=(("field", f),)))
    return findings


_RULE_FNS = {
    "compressor-capabilities": rule_compressor_capabilities,
    "telemetry-fields-reducer": rule_telemetry_fields,
    "pytest-marker-registration": rule_pytest_markers,
    "grace-state-field-roles": rule_grace_state_field_roles,
}


def run_repo_rules(root: Optional[str] = None, *,
                   rules=None,
                   sources: Optional[Dict[str, str]] = None
                   ) -> List[Finding]:
    """Run the named AST rules (default: all) over the repo at ``root``."""
    root = root or repo_root()
    out: List[Finding] = []
    for name in (rules if rules is not None else RULE_NAMES):
        out.extend(_RULE_FNS[name](root, sources))
    return out
