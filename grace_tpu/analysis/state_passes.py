"""graft-sound: the three stateful-semantics audit passes (8–10).

Passes 1–7 audit what the traced program *does* — which collectives it
issues, what bytes cross the wire, whether its numerics saturate. These
three audit what the program does **to its state**, the contract class
every stateful-compression bug lives in:

* **pass 8 ``rng_lineage``** — PRNG keys form a derivation DAG
  (``random_wrap`` roots, ``random_fold_in``/``random_split`` edges,
  ``random_bits`` consumptions). QSGD-style unbiasedness requires
  *independent* stochastic draws per site: two independent consumer sites
  sharing a lineage draw **correlated** quantization noise, and the bias
  that correlation injects scales with world size. The pass reconstructs
  every consumption's lineage path and condemns (a) two
  branch-compatible consumptions of the same lineage with *different*
  draw shapes (a deliberate re-draw of the identical shape is the
  telemetry probe / CSE idiom and is exempt — XLA folds it into one
  draw), and (b) a draw from a **rank-varying** key: ``rng_key`` is a
  replicated field precisely so every rank runs the same schedule
  (cyclictopk's rank-deterministic rotation, shared Top-K negotiation);
  a per-rank key silently breaks that agreement.

* **pass 9 ``rollback_coverage``** — the guard's atomicity contract: on
  a bad step *every* state leaf (params via zeroed updates, optimizer
  state, every GraceState leaf) must be restored bitwise, except the
  leaves :data:`grace_tpu.resilience.guard.GUARD_ROLLBACK_EXCLUDED`
  declares written-through (the guard's own counters, the forward
  ``fallback`` decision). The rollback is ``jnp.where`` selects gated by
  the non-finiteness flag, so the proof obligation is dataflow: a state
  output either *is* its input var, or descends from a ``select_n``
  whose predicate descends from the ``is_finite`` scan and whose
  operands had access to that leaf's input. A new state field that skips
  rollback fails that proof at trace time — not in a chaos drill.

* **pass 10 ``replication_contract``** — at step exit every
  ``GRACE_REPLICATED_FIELDS`` leaf must be *provably* replicated over
  every mesh axis (the same forward rank-variance dataflow pass 1 uses,
  but per output position through the consensus ``cond``), every
  ``GRACE_VARYING_FIELDS`` field should actually vary, and the two
  hand-kept constants are reconciled against ``GraceState._fields`` and
  ``transform.partition_specs`` at 1-D and 2-D meshes so the three
  spellings of the one layout contract can never drift apart.

All three share one abstract-interpretation walk over the body jaxpr
(cached per ``TracedGraph``), tracking per var: the set of state-input
leaves it depends on, guard-select coverage, descent from the guard's
non-finiteness scan, per-mesh-axis rank variance, and PRNG lineage.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Optional, Tuple

from grace_tpu.analysis.passes import (Finding, _ALLTOALL, _GATHERS,
                                       _PERMUTES, _REDUCTIONS, _SCATTER,
                                       _axes_of, _is_var, _stage_of,
                                       _sub_jaxprs_of)
from grace_tpu.analysis.trace import TracedGraph

__all__ = ["STATE_PASS_NAMES", "PASS_FNS", "pass_rng_lineage",
           "pass_rollback_coverage", "pass_replication_contract"]

STATE_PASS_NAMES = ("rng_lineage", "rollback_coverage",
                    "replication_contract")

# Abstract value per jaxpr var: a 5-tuple indexed by these constants.
#   DEP   int bitmask over state-input leaves this value depends on
#   GMASK int bitmask: state leaves i such that the value descends from a
#         guard-gated select_n (predicate descends from is_finite) whose
#         operands depended on leaf i — the rollback-coverage evidence
#   GPRED bool: descends from an is_finite scan (the guard's bad flag)
#   VAR   int bitmask over mesh axes: rank-varying on that axis
#   LIN   PRNG lineage tuple, or None for non-key values
_DEP, _GMASK, _GPRED, _VAR, _LIN = range(5)
_ZERO = (0, 0, False, 0, None)

# Unary shape/dtype ops that forward a key value (and its lineage)
# unchanged in derivation terms.
_LIN_PASSTHROUGH = frozenset({
    "squeeze", "reshape", "broadcast_in_dim", "convert_element_type",
    "transpose", "copy", "random_unwrap", "random_wrap"})


@dataclasses.dataclass(frozen=True)
class _Draw:
    """One stochastic consumption site (``random_bits`` / raw threefry)."""

    lineage: Optional[Tuple]   # key derivation path, None = untracked
    shape: Tuple[int, ...]     # draw output shape
    dtype: str                 # draw output dtype
    ctx: Tuple                 # ((branch_site, branch_idx), ...) context
    stage: str                 # grace/... trace scope
    varmask: int               # mesh-axis variance of the consumed key
    prim: str                  # consuming primitive name


class _Walker:
    """One forward abstract-interpretation walk over a body jaxpr."""

    def __init__(self, axes: Tuple[str, ...], rng_bits: int):
        self.axes = axes
        self.axis_bit = {a: 1 << i for i, a in enumerate(axes)}
        self.rng_bits = rng_bits       # state-leaf bits holding rng_key
        self.env: Dict[Any, Tuple] = {}
        self.draws: List[_Draw] = []
        self._tokens: Dict[Any, int] = {}
        self._sites = 0

    # -- lineage tokens: stable identity for fold data / root operands ----
    def _token(self, v):
        if not _is_var(v):
            return ("lit", str(getattr(v, "val", v)))
        t = self._tokens.get(v)
        if t is None:
            t = self._tokens[v] = len(self._tokens)
        return ("var", t)

    def _get(self, v) -> Tuple:
        if not _is_var(v):
            return _ZERO
        return self.env.get(v, _ZERO)

    def _join(self, vals) -> Tuple:
        dep = gmask = var = 0
        gpred = False
        for a in vals:
            dep |= a[_DEP]
            gmask |= a[_GMASK]
            gpred = gpred or a[_GPRED]
            var |= a[_VAR]
        return (dep, gmask, gpred, var, None)

    # -- the walk ---------------------------------------------------------
    def walk(self, jaxpr, ctx: Tuple = ()):
        for v in jaxpr.constvars:
            self.env.setdefault(v, _ZERO)
        for eqn in jaxpr.eqns:
            self._eqn(eqn, ctx)

    def _eqn(self, eqn, ctx: Tuple):
        name = eqn.primitive.name
        ins = [self._get(v) for v in eqn.invars]
        joined = self._join(ins)
        out = joined

        if name == "axis_index":
            var = joined[_VAR]
            for a in _axes_of(eqn):
                var |= self.axis_bit.get(a, 0)
            out = (joined[_DEP], joined[_GMASK], joined[_GPRED], var, None)
        elif name in _REDUCTIONS or name in _GATHERS:
            # Full-axis reduction/gather: every rank computes the identical
            # result over that axis (axis_index_groups would break that).
            var = joined[_VAR]
            if eqn.params.get("axis_index_groups") is None:
                for a in _axes_of(eqn):
                    var &= ~self.axis_bit.get(a, 0)
            out = (joined[_DEP], joined[_GMASK], joined[_GPRED], var, None)
        elif name in _PERMUTES or name in _ALLTOALL or name in _SCATTER:
            var = joined[_VAR]
            for a in _axes_of(eqn):
                var |= self.axis_bit.get(a, 0)
            out = (joined[_DEP], joined[_GMASK], joined[_GPRED], var, None)
        elif name == "is_finite":
            out = (joined[_DEP], joined[_GMASK], True, joined[_VAR], None)
        elif name == "select_n":
            pred, data = ins[0], ins[1:]
            dj = self._join(data)
            gmask = dj[_GMASK] | pred[_GMASK]
            if pred[_GPRED]:
                # A guard-gated select: whatever state leaves its operands
                # could restore, the output is covered for.
                gmask |= dj[_DEP]
            lins = {a[_LIN] for a in data}
            lin = lins.pop() if len(lins) == 1 else None
            out = (dj[_DEP] | pred[_DEP], gmask,
                   dj[_GPRED] or pred[_GPRED], dj[_VAR] | pred[_VAR], lin)
        elif name == "random_wrap":
            src = ins[0] if ins else _ZERO
            lin = src[_LIN]
            if lin is None:
                root_dep = src[_DEP] & self.rng_bits
                if root_dep:
                    lin = (("root", root_dep),)
                else:
                    lin = (("root", self._token(eqn.invars[0])),)
            out = (joined[_DEP], joined[_GMASK], joined[_GPRED],
                   joined[_VAR], lin)
        elif name == "random_fold_in":
            key = ins[0] if ins else _ZERO
            lin = None
            if key[_LIN] is not None and len(eqn.invars) > 1:
                lin = key[_LIN] + (("fold", self._token(eqn.invars[1])),)
            out = (joined[_DEP], joined[_GMASK], joined[_GPRED],
                   joined[_VAR], lin)
        elif name == "random_split":
            key = ins[0] if ins else _ZERO
            lin = (key[_LIN] + (("split",),)
                   if key[_LIN] is not None else None)
            out = (joined[_DEP], joined[_GMASK], joined[_GPRED],
                   joined[_VAR], lin)
        elif name in ("slice", "dynamic_slice"):
            src = ins[0] if ins else _ZERO
            lin = src[_LIN]
            if lin is not None:
                if name == "slice":
                    at = tuple(eqn.params.get("start_indices", ()))
                else:
                    at = tuple(self._token(v) for v in eqn.invars[1:])
                lin = lin + (("at", at),)
            out = (joined[_DEP], joined[_GMASK], joined[_GPRED],
                   joined[_VAR], lin)
        elif name in _LIN_PASSTHROUGH and len(eqn.invars) == 1:
            out = (joined[_DEP], joined[_GMASK], joined[_GPRED],
                   joined[_VAR], ins[0][_LIN])
        elif name == "random_bits":
            key = ins[0] if ins else _ZERO
            self._record(eqn, key, ctx)
        elif name == "threefry2x32":
            # Raw counter-mode use (a codec bypassing the key dtype): a
            # consumption when any operand carries lineage.
            keyed = [a for a in ins if a[_LIN] is not None]
            if keyed:
                self._record(eqn, keyed[0], ctx)
        elif name == "cond":
            out = self._cond(eqn, ins, ctx)
            if out is not None:
                return                      # outputs already bound
            out = joined
        else:
            subs = _sub_jaxprs_of(eqn)
            if subs:
                out = self._call(eqn, subs, ins, joined, ctx)
                if out is None:
                    return                  # outputs already bound
        for v in eqn.outvars:
            self.env[v] = out

    def _record(self, eqn, key: Tuple, ctx: Tuple):
        aval = eqn.outvars[0].aval
        self.draws.append(_Draw(
            lineage=key[_LIN], shape=tuple(aval.shape),
            dtype=str(aval.dtype), ctx=ctx, stage=_stage_of(eqn),
            varmask=key[_VAR], prim=eqn.primitive.name))

    def _cond(self, eqn, ins, ctx: Tuple):
        """Per-position branch join: dep/variance union, coverage
        intersection (a leaf is only *proven* restored when every branch
        restores it), predicate variance OR-ed into every output — the
        per-position precision is what keeps the consensus ``cond``'s
        replicated state passthroughs provably replicated."""
        site = self._sites
        self._sites += 1
        pred = ins[0] if ins else _ZERO
        ops = eqn.invars[1:]
        branches = [getattr(b, "jaxpr", b) for b in eqn.params["branches"]]
        branch_outs = []
        passthrough = []     # per branch: outvar position -> operand index
        for k, sub in enumerate(branches):
            if len(sub.invars) == len(ops):
                for sv, ov in zip(sub.invars, ops):
                    self.env[sv] = self._get(ov)
                iv_index = {sv: m for m, sv in enumerate(sub.invars)}
                passthrough.append({j: iv_index[ov]
                                    for j, ov in enumerate(sub.outvars)
                                    if _is_var(ov) and ov in iv_index})
            else:
                coarse = self._join(ins)
                for sv in sub.invars:
                    self.env[sv] = coarse
                passthrough.append({})
            self.walk(sub, ctx + ((site, k),))
            branch_outs.append([self._get(ov) for ov in sub.outvars])
        if not all(len(b) == len(eqn.outvars) for b in branch_outs):
            return None
        for j, v in enumerate(eqn.outvars):
            # Passthrough refinement: when EVERY branch forwards the same
            # operand untouched, the output equals that operand no matter
            # which branch runs — the predicate's variance is irrelevant.
            # This is what keeps replicated state leaves provably
            # replicated through an audit cond whose predicate is
            # legitimately shard-varying.
            fwd = {p.get(j, -1 - k) for k, p in enumerate(passthrough)}
            if len(fwd) == 1:
                self.env[v] = self._get(ops[fwd.pop()])
                continue
            cols = [b[j] for b in branch_outs]
            dep = pred[_DEP]
            var = pred[_VAR]
            gmask = cols[0][_GMASK]
            gpred = pred[_GPRED]
            lins = {c[_LIN] for c in cols}
            for c in cols:
                dep |= c[_DEP]
                var |= c[_VAR]
                gmask &= c[_GMASK]
                gpred = gpred or c[_GPRED]
            self.env[v] = (dep, gmask, gpred, var,
                           lins.pop() if len(lins) == 1 else None)
        return True

    def _call(self, eqn, subs, ins, joined, ctx: Tuple):
        """pjit/closed_call/scan/remat: single sub-jaxpr with matching
        arities maps per position (scan's carry+xs arities line up too);
        anything else falls back to the coarse join — still walked, so
        consumptions inside are never missed."""
        if len(subs) == 1 and len(subs[0].invars) == len(eqn.invars):
            sub = subs[0]
            for sv, ov in zip(sub.invars, eqn.invars):
                self.env[sv] = self._get(ov)
            self.walk(sub, ctx)
            if len(sub.outvars) == len(eqn.outvars):
                for v, ov in zip(eqn.outvars, sub.outvars):
                    self.env[v] = self._get(ov)
                return None
            return joined
        coarse = (joined[_DEP], joined[_GMASK], joined[_GPRED],
                  joined[_VAR], None)
        for sub in subs:
            for sv in sub.invars:
                self.env[sv] = coarse
            self.walk(sub, ctx)
        return coarse


def _analyze(traced: TracedGraph) -> _Walker:
    """The shared walk, cached on the TracedGraph (one walk serves all
    three passes in a ``run_passes`` sweep)."""
    cached = traced.meta.get("_graft_sound")
    if cached is not None:
        return cached
    axes = traced.axes
    rng_bits = 0
    for i, (path, _v) in enumerate(traced.state_in_vars):
        if _field_of(path, traced.grace_prefixes) == "rng_key":
            rng_bits |= 1 << i
    w = _Walker(axes, rng_bits)
    leaf_bit = {}
    for i, (_path, v) in enumerate(traced.state_in_vars):
        leaf_bit[v] = leaf_bit.get(v, 0) | (1 << i)
    for v in traced.body.invars:
        var = 0
        for ai, a in enumerate(axes):
            if traced.varying_for(a).get(v, True):
                var |= 1 << ai
        dep = leaf_bit.get(v, 0)
        # A key-dtype rng_key leaf is consumed without a random_wrap, so
        # the lineage root is seeded on the invar itself.
        lin = (("root", dep & rng_bits),) if dep & rng_bits else None
        w.env[v] = (dep, 0, False, var, lin)
    w.walk(traced.body)
    traced.meta["_graft_sound"] = w
    return w


def _field_of(path: str, prefixes: Tuple[str, ...]) -> Optional[str]:
    """The GraceState field a state-leaf path belongs to, or None for
    non-grace leaves (params, guard counters, optimizer moments)."""
    for pre in sorted(prefixes, key=len, reverse=True):
        if pre == "":
            return path.split("/", 1)[0]
        if path.startswith(pre + "/"):
            return path[len(pre) + 1:].split("/", 1)[0]
    return None


def _ctx_compatible(a: Tuple, b: Tuple) -> bool:
    """Two draw sites can co-occur in one execution iff they agree on
    every branch site they share (different arms of one cond/switch are
    mutually exclusive — the adapt ladder's rungs never cross-correlate)."""
    da = dict(a)
    return all(da.get(site, k) == k for site, k in b)


# ---------------------------------------------------------------------------
# pass 8: rng lineage
# ---------------------------------------------------------------------------

def pass_rng_lineage(traced: TracedGraph) -> List[Finding]:
    """Independent stochastic sites must consume independently derived
    keys, and every consumed key must be rank-replicated."""
    w = _analyze(traced)
    findings: List[Finding] = []

    for d in w.draws:
        if d.varmask:
            axes = [a for i, a in enumerate(traced.axes)
                    if d.varmask & (1 << i)]
            findings.append(Finding(
                pass_name="rng_lineage", config=traced.name,
                severity="error", stage=d.stage,
                message=(
                    f"stochastic draw ({d.prim} -> {d.dtype}{d.shape}) "
                    f"consumes a rank-varying key (axes "
                    f"{', '.join(axes)}) — rng_key is a replicated field "
                    "so every rank draws the same schedule; a per-rank "
                    "key desyncs rank-deterministic selection "
                    "(cyclictopk rotation, shared Top-K negotiation)"),
                details=(("axes", tuple(axes)), ("shape", d.shape))))

    by_lin: Dict[Tuple, List[_Draw]] = {}
    for d in w.draws:
        if d.lineage is not None:
            by_lin.setdefault(d.lineage, []).append(d)
    reported = set()
    for lin, group in by_lin.items():
        for i in range(len(group)):
            for j in range(i + 1, len(group)):
                a, b = group[i], group[j]
                if (a.shape, a.dtype) == (b.shape, b.dtype):
                    # The identical re-draw: the telemetry error probe /
                    # chunk-0 probe-encode idiom — XLA CSEs it into ONE
                    # draw, so the sites are the same draw, not two
                    # correlated ones.
                    continue
                if not _ctx_compatible(a.ctx, b.ctx):
                    continue
                key = (lin, tuple(sorted(((a.shape, a.dtype),
                                          (b.shape, b.dtype)))))
                if key in reported:
                    continue
                reported.add(key)
                findings.append(Finding(
                    pass_name="rng_lineage", config=traced.name,
                    severity="error", stage=a.stage or b.stage,
                    message=(
                        f"two independent stochastic sites share one rng "
                        f"lineage: {a.dtype}{a.shape} at "
                        f"'{a.stage or '?'}' and {b.dtype}{b.shape} at "
                        f"'{b.stage or '?'}' draw from the same derived "
                        "key — correlated quantization noise breaks the "
                        "unbiased-estimator contract; fold a distinct "
                        "site index into each key"),
                    details=(("shapes", (a.shape, b.shape)),
                             ("stages", (a.stage, b.stage)))))
    return findings


# ---------------------------------------------------------------------------
# pass 9: rollback coverage
# ---------------------------------------------------------------------------

def pass_rollback_coverage(traced: TracedGraph) -> List[Finding]:
    """Every state leaf the guarded step writes must be restored by a
    guard-gated select or declared in ``GUARD_ROLLBACK_EXCLUDED``. Only
    meaningful on guarded train-step traces (``meta['guard']``); update-
    mode and unguarded traces have no rollback contract to audit."""
    if traced.meta.get("guard") is None:
        return []
    if not traced.state_in_vars or not traced.state_out_vars:
        return []
    from grace_tpu.resilience.guard import GUARD_ROLLBACK_EXCLUDED

    w = _analyze(traced)
    excluded = set(GUARD_ROLLBACK_EXCLUDED)
    findings: List[Finding] = []
    for i, ((path, vin), (_po, vout)) in enumerate(
            zip(traced.state_in_vars, traced.state_out_vars)):
        if _is_var(vout) and vout is vin:
            continue                       # passed through bitwise
        if set(path.split("/")) & excluded:
            continue                       # declared written-through
        a = w._get(vout)
        if a[_GMASK] & (1 << i):
            continue                       # proven restored by a select
        findings.append(Finding(
            pass_name="rollback_coverage", config=traced.name,
            severity="error",
            message=(
                f"state leaf '{path}' is written by the guarded step but "
                "never restored by a rollback select: on a bad step its "
                "new (possibly poisoned) value survives. Route it "
                "through the guard's jnp.where rollback, or — if it is "
                "deliberately written through — add its field to "
                "resilience.guard.GUARD_ROLLBACK_EXCLUDED"),
            details=(("path", path),)))
    return findings


# ---------------------------------------------------------------------------
# pass 10: replication contract
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _contract_drift() -> Tuple[str, ...]:
    """Static reconciliation of the three spellings of the layout
    contract: the two field-role constants, ``GraceState._fields``, and
    ``partition_specs`` at a 1-D and a 2-D mesh. Config-independent,
    computed once per process."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from grace_tpu import transform as T

    msgs: List[str] = []
    rep, varf = set(T.GRACE_REPLICATED_FIELDS), set(T.GRACE_VARYING_FIELDS)
    fields = set(T.GraceState._fields)
    overlap = rep & varf
    if overlap:
        msgs.append(f"fields {sorted(overlap)} appear in BOTH "
                    "GRACE_REPLICATED_FIELDS and GRACE_VARYING_FIELDS")
    missing = fields - (rep | varf)
    if missing:
        msgs.append(f"GraceState fields {sorted(missing)} appear in "
                    "neither GRACE_REPLICATED_FIELDS nor "
                    "GRACE_VARYING_FIELDS — extend one of the constants")
    ghost = (rep | varf) - fields
    if ghost:
        msgs.append(f"field-role constants name {sorted(ghost)} which are "
                    "not GraceState fields")
    if not set(T.GRACE_OBSERVATIONAL_FIELDS) <= varf:
        msgs.append("GRACE_OBSERVATIONAL_FIELDS is not a subset of "
                    "GRACE_VARYING_FIELDS")

    leaf = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    state = T.GraceState(**{f: leaf for f in T.GraceState._fields})
    for mesh in (T.MeshSpec(), T.MeshSpec(dp_axis="dp", fsdp_axis="fsdp")):
        specs = T.partition_specs(state, mesh)
        vspec = mesh.varying_spec()
        for f in T.GraceState._fields:
            got = getattr(specs, f)
            want = vspec if f in varf else P()
            if got != want:
                msgs.append(
                    f"partition_specs disagrees with the field-role "
                    f"constants at mesh {mesh.axes}: field '{f}' gets "
                    f"{got} but its role says {want}")
    return tuple(msgs)


def pass_replication_contract(traced: TracedGraph) -> List[Finding]:
    """At step exit every replicated GraceState leaf must be provably
    replicated over every mesh axis; varying fields should actually
    vary; and the hand-kept constants must agree with partition_specs.

    Consensus scoping: the audit/repair path's writes (masked-broadcast
    repairs, divergence accounting) are functions of the fingerprint
    comparison, which is *definitionally* per-shard data on any axis the
    audit collectives don't span — their replication over non-exchange
    axes holds by the healthy-run induction (identical inputs produce
    identical decisions), not by dataflow, and no static analysis can
    prove an induction over fault states. So on consensus-enabled traces
    the replicated-leaf check applies to the exchange axis only — the
    axis the repair broadcasts actually restore — while non-consensus
    traces are checked over every mesh axis."""
    findings = [
        Finding(pass_name="replication_contract", config=traced.name,
                severity="error", message=m, details=())
        for m in _contract_drift()]
    if not traced.state_out_vars:
        return findings
    from grace_tpu.transform import (GRACE_REPLICATED_FIELDS,
                                     GRACE_VARYING_FIELDS)

    w = _analyze(traced)
    full = (1 << len(traced.axes)) - 1
    check = full
    if traced.meta.get("consensus"):
        check = 1 << traced.axes.index(traced.axis_name)
    field_var: Dict[Tuple[str, str], int] = {}
    for path, vout in traced.state_out_vars:
        field = _field_of(path, traced.grace_prefixes)
        if field is None:
            continue
        a = w._get(vout)
        if field in GRACE_REPLICATED_FIELDS and a[_VAR] & check:
            axes = [ax for i, ax in enumerate(traced.axes)
                    if a[_VAR] & check & (1 << i)]
            findings.append(Finding(
                pass_name="replication_contract", config=traced.name,
                severity="error",
                message=(
                    f"replicated-field leaf '{path}' leaves the step "
                    f"rank-varying over {', '.join(axes)} — a "
                    "rank-varying write into a GRACE_REPLICATED_FIELDS "
                    "field desyncs replicas (the adapt-rung desync "
                    "class); make the write derive from full-axis "
                    "collectives, or move the field to "
                    "GRACE_VARYING_FIELDS and partition_specs"),
                details=(("path", path), ("axes", tuple(axes)))))
        if field in GRACE_VARYING_FIELDS:
            k = (path.rsplit(field, 1)[0], field)
            field_var[k] = field_var.get(k, 0) | a[_VAR]
    for (_prefix, field), var in sorted(field_var.items()):
        if var != full:
            missing = [ax for i, ax in enumerate(traced.axes)
                       if not (var & (1 << i))]
            findings.append(Finding(
                pass_name="replication_contract", config=traced.name,
                severity="warning",
                message=(
                    f"varying field '{field}' has no leaf that actually "
                    f"varies over {', '.join(missing)} — it is sharded "
                    "by partition_specs but provably replicated; either "
                    "the state is dead weight at world size or the "
                    "field belongs in GRACE_REPLICATED_FIELDS"),
                details=(("field", field), ("axes", tuple(missing)))))
    return findings


PASS_FNS = {
    "rng_lineage": pass_rng_lineage,
    "rollback_coverage": pass_rollback_coverage,
    "replication_contract": pass_replication_contract,
}
