"""The four composable jaxpr audit passes.

Every pass takes a :class:`~grace_tpu.analysis.trace.TracedGraph` and
returns a list of :class:`Finding`. Shared machinery:

* **recursive equation walk** — collectives hide inside ``cond`` branches,
  ``while`` bodies, ``pjit``/``custom_*_call`` sub-jaxprs and (post-vmap)
  batched shapes; every pass sees the whole nest;
* **replication analysis** — a forward dataflow pass over the body jaxpr:
  a value is *rank-varying* when it descends from a rank-varying input
  (sharded batch, per-rank residuals — seeded by the tracer from
  ``partition_specs``) or from ``axis_index``, and becomes *replicated*
  again when it passes through a full-axis ``psum``/``all_gather`` (every
  rank computes the identical reduction). ``ppermute``/``all_to_all``
  outputs are rank-varying by construction. This is what lets the
  collective-consistency pass bless the dense-escape cond (its predicate
  is the replicated fallback flag) while condemning a cond whose predicate
  descends from local data;
* **stage attribution** — each equation's ``source_info.name_stack``
  carries the ``grace/...`` scope names from
  :mod:`grace_tpu.telemetry.scopes`, so findings name the pipeline stage
  (``grace/exchange``, ``grace/consensus``, ...) they sit in.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from grace_tpu.analysis.trace import TracedGraph

__all__ = ["Finding", "PASS_NAMES", "run_passes",
           "pass_collective_consistency", "pass_bit_exactness",
           "pass_wire_reconciliation", "pass_signature_stability",
           "collective_signature", "count_recv_bytes",
           "count_recv_link_bytes"]

# Cross-replica primitives, by behavior class. `pbroadcast` is check_rep
# bookkeeping (identity on every rank), not a wire collective.
_REDUCTIONS = frozenset({"psum", "psum2", "pmax", "pmin", "pmean"})
_GATHERS = frozenset({"all_gather", "all_gather_invariant"})
_PERMUTES = frozenset({"ppermute", "pshuffle"})
_ALLTOALL = frozenset({"all_to_all"})
_SCATTER = frozenset({"reduce_scatter"})
COLLECTIVE_PRIMS = _REDUCTIONS | _GATHERS | _PERMUTES | _ALLTOALL | _SCATTER

_CALLBACK_PRIMS = frozenset({
    "io_callback", "debug_callback", "pure_callback", "callback",
    "outside_call", "host_callback_call"})

# Passes 5-7 (graft-flow, ISSUE 9) live in analysis/flow.py on the
# dependence-graph layer and passes 8-10 (graft-sound, ISSUE 20) in
# analysis/state_passes.py on the stateful-semantics layer; both sets are
# resolved lazily by run_passes — the names are plain strings here so
# config registration and CLI selection never import those modules (which
# import this module) at module-load time.
PASS_NAMES = ("collective_consistency", "bit_exactness",
              "wire_reconciliation", "signature_stability",
              "overlap_schedulability", "numeric_safety",
              "memory_footprint", "rng_lineage", "rollback_coverage",
              "replication_contract")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding. ``severity`` is ``'error'`` (CI-failing) or
    ``'warning'``; ``stage`` is the ``grace/...`` trace-scope the offending
    equation sits in (empty when unattributable)."""

    pass_name: str
    config: str
    severity: str
    message: str
    stage: str = ""
    details: Tuple[Tuple[str, Any], ...] = ()

    def as_dict(self) -> dict:
        return {"pass": self.pass_name, "config": self.config,
                "severity": self.severity, "message": self.message,
                "stage": self.stage, **dict(self.details)}


def _stage_of(eqn) -> str:
    """The canonical stage the equation was traced under — the shared
    longest-prefix vocabulary match
    (:func:`grace_tpu.telemetry.scopes.match_stage`), applied to the
    equation's ``name_stack``. The profiler trace analyzer
    (:mod:`grace_tpu.profiling`) attributes device spans with literally the
    same function, so static findings and measured time name stages
    identically."""
    from grace_tpu.telemetry.scopes import match_stage

    try:
        stack = str(eqn.source_info.name_stack)
    except Exception:
        return ""
    return match_stage(stack)


def _axes_of(eqn) -> Tuple[str, ...]:
    """The mesh axis names a collective equation operates over."""
    p = eqn.params
    axes = p.get("axes", p.get("axis_name", ()))
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def _sub_jaxprs_of(eqn):
    out = []
    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for item in vs:
            inner = getattr(item, "jaxpr", item)
            if hasattr(inner, "eqns") and hasattr(inner, "invars"):
                out.append(inner)
    return out


def _is_var(v) -> bool:
    return hasattr(v, "aval") and not hasattr(v, "val")


def _aval_nbytes(aval) -> int:
    return int(np.prod(aval.shape, dtype=np.int64)) * aval.dtype.itemsize


# ---------------------------------------------------------------------------
# replication (rank-variance) dataflow
# ---------------------------------------------------------------------------

def _propagate_variance(jaxpr, axis_name: str,
                        seed: Dict[Any, bool]) -> Dict[Any, bool]:
    """Forward rank-variance over one jaxpr (recursing into sub-jaxprs).

    Conservative in the safe direction: unknown structure propagates
    variance, replication is only granted by full-axis reductions/gathers.
    """
    var: Dict[Any, bool] = {}
    for v in jaxpr.invars:
        var[v] = seed.get(v, True)
    for v in jaxpr.constvars:
        var[v] = False

    def lookup(v) -> bool:
        return var.get(v, False) if _is_var(v) else False

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        any_in = any(lookup(v) for v in eqn.invars)
        if name == "axis_index":
            out = axis_name in _axes_of(eqn) or any_in
        elif name in _REDUCTIONS or name in _GATHERS:
            # Full-axis reduction/gather over our axis: every rank computes
            # the identical result (axis_index_groups would break that).
            full = (axis_name in _axes_of(eqn)
                    and eqn.params.get("axis_index_groups") is None)
            out = False if full else any_in
        elif name in _PERMUTES or name in _ALLTOALL or name in _SCATTER:
            # Rank-varying by construction over the axes they permute; a
            # permute over a DIFFERENT mesh axis (the 2-D dp×fsdp case)
            # moves values within this axis's groups and leaves this
            # axis's variance as the operands had it.
            out = axis_name in _axes_of(eqn) or any_in
        elif name == "pbroadcast":
            out = any_in
        else:
            subs = _sub_jaxprs_of(eqn)
            if subs:
                # Map operand variance into each sub-jaxpr positionally
                # where arities line up (cond drops the predicate operand;
                # other call-like prims pass operands straight through) and
                # OR the sub-results; fall back to any_in otherwise.
                out_flags = []
                for sub in subs:
                    if name == "cond":
                        ops = eqn.invars[1:]
                    else:
                        ops = eqn.invars
                    if len(sub.invars) == len(ops):
                        sub_seed = {sv: lookup(ov)
                                    for sv, ov in zip(sub.invars, ops)}
                        sub_var = _propagate_variance(sub, axis_name,
                                                      sub_seed)
                        out_flags.append(any(
                            sub_var.get(ov, any_in) if _is_var(ov) else False
                            for ov in sub.outvars))
                    else:
                        out_flags.append(any_in)
                out = any(out_flags) or (name == "cond"
                                         and lookup(eqn.invars[0]))
            else:
                out = any_in
        for v in eqn.outvars:
            var[v] = out
    return var


# ---------------------------------------------------------------------------
# pass 1: collective consistency across cond/while branches
# ---------------------------------------------------------------------------

def collective_signature(jaxpr) -> Tuple:
    """Ordered tuple of (prim, axes, operand shapes/dtypes, schedule params)
    for every collective in ``jaxpr``, recursing into nested jaxprs in
    equation order. Two branches with equal signatures issue the same
    collective sequence and can never deadlock against each other."""
    sig = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            operands = tuple(
                (tuple(v.aval.shape), str(v.aval.dtype))
                for v in eqn.invars if _is_var(v))
            extra = tuple(sorted(
                (k, str(v)) for k, v in eqn.params.items()
                if k in ("perm", "all_gather_dimension", "tiled",
                         "axis_index_groups", "split_axis", "concat_axis")))
            sig.append((name, _axes_of(eqn), operands, extra))
        else:
            for sub in _sub_jaxprs_of(eqn):
                sig.extend(collective_signature(sub))
    return tuple(sig)


def _signature_axes(sig, mesh_axes) -> set:
    """The mesh axes a collective signature's entries span."""
    return {a for _name, axes, _ops, _extra in sig
            for a in axes if a in mesh_axes}


def pass_collective_consistency(traced: TracedGraph) -> List[Finding]:
    """Branch-divergent collective sequences under a predicate that is not
    provably replicated: the cross-rank deadlock/desync class. A cond whose
    branches differ (the dense escape hatch, the consensus audit gate) is
    legal exactly when its predicate is replicated **over every mesh axis
    the divergent collectives span** — every rank that must rendezvous
    takes the same branch. On a 2-D dp×fsdp mesh the analysis is
    per-axis: a predicate that varies only over fsdp may legally gate a
    dp-axis collective (the dp peers share an fsdp index, so they agree),
    while a predicate replicated over the *wrong* axis — e.g. psummed
    over fsdp but still dp-varying, gating a dp collective — is condemned.
    """
    findings: List[Finding] = []
    axes = traced.axes

    def walk(jaxpr, var_maps):
        def lookup(axis, v):
            m = var_maps[axis]
            return m.get(v, False) if _is_var(v) else False

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "cond":
                branches = [getattr(b, "jaxpr", b)
                            for b in eqn.params["branches"]]
                sigs = [collective_signature(b) for b in branches]
                if any(s != sigs[0] for s in sigs[1:]):
                    spanned = set()
                    for s in sigs:
                        spanned |= _signature_axes(s, axes)
                    bad = sorted(a for a in spanned
                                 if lookup(a, eqn.invars[0]))
                    if bad:
                        findings.append(Finding(
                            pass_name="collective_consistency",
                            config=traced.name, severity="error",
                            stage=_stage_of(eqn),
                            message=(
                                "lax.cond branches issue different "
                                "collective sequences "
                                f"({[len(s) for s in sigs]} collectives per "
                                "branch) spanning mesh "
                                f"axis(es) {sorted(spanned)} and the "
                                "predicate is derived from data that "
                                f"varies over {bad} — ranks that must "
                                "rendezvous can take different branches "
                                "and deadlock/desync at the first "
                                "mismatched collective"),
                            details=(("world", traced.world),
                                     ("varying_axes", tuple(bad)))))
            elif name == "while":
                cond_j = getattr(eqn.params.get("cond_jaxpr"), "jaxpr",
                                 eqn.params.get("cond_jaxpr"))
                body_j = getattr(eqn.params.get("body_jaxpr"), "jaxpr",
                                 eqn.params.get("body_jaxpr"))
                sig = (collective_signature(body_j)
                       if body_j is not None else ())
                sig += (collective_signature(cond_j)
                        if cond_j is not None else ())
                spanned = _signature_axes(sig, axes) or (
                    set(axes) if sig else set())
                if sig and any(lookup(a, v) for a in spanned
                               for v in eqn.invars):
                    findings.append(Finding(
                        pass_name="collective_consistency",
                        config=traced.name, severity="error",
                        stage=_stage_of(eqn),
                        message=(
                            f"while loop contains {len(sig)} collective(s) "
                            "but its carry includes rank-varying data — "
                            "trip counts can diverge across ranks and "
                            "strand a subset in the collective"),
                        details=(("world", traced.world),)))
            # Recurse with operand variance mapped into the sub-jaxpr.
            for sub in _sub_jaxprs_of(eqn):
                ops = eqn.invars[1:] if name == "cond" else eqn.invars
                sub_maps = {}
                for a in axes:
                    if len(sub.invars) == len(ops):
                        seed = {sv: lookup(a, ov)
                                for sv, ov in zip(sub.invars, ops)}
                    else:
                        seed = {sv: True for sv in sub.invars}
                    sub_maps[a] = _propagate_variance(sub, a, seed)
                walk(sub, sub_maps)

    walk(traced.body, {a: _propagate_variance(traced.body, a,
                                              traced.varying_for(a))
                       for a in axes})
    return findings


# ---------------------------------------------------------------------------
# pass 2: bit-exactness of cross-replica reductions
# ---------------------------------------------------------------------------

def pass_bit_exactness(traced: TracedGraph) -> List[Finding]:
    """Bit-pattern data must never ride a float-space cross-replica
    reduction (the PR-3 bug class: ``-0.0 + 0.0 == +0.0`` flips sign bits,
    NaN payloads are not preserved through float adds).

    Taint: values whose *numeric content encodes a bit pattern* — produced
    by ``bitcast_convert_type`` to an integer dtype (fingerprint words,
    checksum folds, masked-broadcast words), propagated through arithmetic
    and value conversions, and cleared by a bitcast back to float (which
    reconstructs the original values). A float-dtype
    ``psum``/``pmean``/... over tainted data is the finding; integer-space
    reductions (``masked_broadcast``'s uint psum) and gathers (which move
    bits verbatim) are exactly the sanctioned alternatives.
    """
    findings: List[Finding] = []

    def walk(jaxpr, seed_taint: Dict[Any, bool]):
        taint: Dict[Any, bool] = {}
        for v in jaxpr.invars:
            taint[v] = seed_taint.get(v, False)
        for v in jaxpr.constvars:
            taint[v] = False

        def lookup(v):
            return taint.get(v, False) if _is_var(v) else False

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            any_in = any(lookup(v) for v in eqn.invars)
            if name == "bitcast_convert_type":
                new_dtype = np.dtype(eqn.params["new_dtype"])
                out = not np.issubdtype(new_dtype, np.floating)
            elif name in _REDUCTIONS:
                if (any(a in _axes_of(eqn) for a in traced.axes) and any(
                        lookup(v) and np.issubdtype(v.aval.dtype,
                                                    np.floating)
                        for v in eqn.invars if _is_var(v))):
                    findings.append(Finding(
                        pass_name="bit_exactness",
                        config=traced.name, severity="error",
                        stage=_stage_of(eqn),
                        message=(
                            f"float-dtype {name} over bit-pattern data "
                            "(descends from an integer bitcast: "
                            "fingerprint/checksum/masked-broadcast words) "
                            "— float adds alias -0.0/+0.0 and drop NaN "
                            "payloads; reduce in integer bit space "
                            "(comm.masked_broadcast) instead"),
                        details=(("world", traced.world),)))
                out = any_in
            else:
                subs = _sub_jaxprs_of(eqn)
                for sub in subs:
                    ops = eqn.invars[1:] if name == "cond" else eqn.invars
                    if len(sub.invars) == len(ops):
                        walk(sub, {sv: lookup(ov)
                                   for sv, ov in zip(sub.invars, ops)})
                    else:
                        walk(sub, {sv: any_in for sv in sub.invars})
                out = any_in
            for v in eqn.outvars:
                taint[v] = out

    walk(traced.body, {})
    return findings


# ---------------------------------------------------------------------------
# pass 3: wire-byte reconciliation against Communicator.recv_wire_bytes
# ---------------------------------------------------------------------------

def _group_size(eqn, world: int) -> int:
    """Ranks one collective actually spans: the ``axis_index_groups`` group
    size when set (the hierarchical communicator's nested sub-axes —
    cross-slice peers, intra-slice peers), else the whole axis. Groups
    partition the axis into equal-size sets, so the first group's length is
    the per-rank schedule width."""
    groups = eqn.params.get("axis_index_groups")
    if not groups:
        return world
    return len(groups[0])


def _link_tier(eqn, world: int, topology) -> int:
    """Worst link tier this collective's schedule touches under
    ``topology`` — 0 = ICI (intra-slice), 1 = DCN (cross-slice), 2 = WAN
    (cross-region): the critical-path attribution of
    :meth:`~grace_tpu.core.Communicator.recv_link_bytes`, derived from the
    *traced* rank sets instead of the hand-maintained model:

    * a ``ppermute`` crosses a boundary iff any (src, dst) pair sits on
      different sides of it (a flat ring's wrap-around neighbor pair
      always does once the axis spans the boundary — which is why flat
      rings price at the worst tier the axis spans);
    * a grouped collective crosses iff any group mixes sides (the
      hierarchical comm's cross-slice groups cross DCN yet stay inside a
      region; its cross-region groups cross WAN; intra-slice groups
      never cross anything);
    * an ungrouped full-axis collective crosses whatever the axis does.
    """
    if topology is None or not topology.crosses_dcn(world):
        return 0
    spans = [topology.slice_size]
    if topology.region_size is not None and topology.crosses_wan(world):
        spans.append(topology.region_size)

    def crosses(span: int) -> bool:
        if eqn.primitive.name in _PERMUTES:
            perm = eqn.params.get("perm") or ()
            return any(int(a) // span != int(b) // span for a, b in perm)
        groups = eqn.params.get("axis_index_groups")
        if groups:
            return any(len({int(r) // span for r in grp}) > 1
                       for grp in groups)
        return True

    tier = 0
    for i, span in enumerate(spans, start=1):
        if crosses(span):
            tier = i
    return tier


def count_recv_bytes(jaxpr, axis_name: str, world: int) -> int:
    """Logical bytes RECEIVED per rank for the collectives in ``jaxpr`` —
    the scalar view of :func:`count_recv_link_bytes`."""
    return sum(count_recv_link_bytes(jaxpr, axis_name, world, None))


def count_recv_link_bytes(jaxpr, axis_name: str, world: int,
                          topology) -> Tuple[int, int, int]:
    """Per-rank received bytes of the collectives in ``jaxpr``, split into
    ``(ici, dcn, wan)`` by the worst boundary each collective's traced
    schedule crosses under ``topology`` (recursive; cond branches count as
    the branch with the larger total — an upper bound matching how the wire
    model prices the live path). ``topology=None`` attributes everything to
    ICI (the single-slice scalar count).

    Per-collective accounting mirrors the standard schedules the model in
    :meth:`grace_tpu.core.Communicator.recv_wire_bytes` assumes, over the
    ranks the collective actually spans (``axis_index_groups`` narrows a
    collective to its group — the hierarchical communicator's nested
    sub-axes): ring all-reduce moves ``2·n·(G-1)/G``; a gather receives
    every other member's shard ``n·(G-1)``; a ppermute hop receives one
    full operand; all_to_all and reduce_scatter receive ``n·(G-1)/G``.
    """
    tiers = [0, 0, 0]
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS and axis_name in _axes_of(eqn):
            nbytes = sum(_aval_nbytes(v.aval) for v in eqn.invars
                         if _is_var(v))
            g = _group_size(eqn, world)
            if name in _REDUCTIONS:
                got = 2 * nbytes * (g - 1) // max(1, g)
            elif name in _GATHERS:
                got = nbytes * max(0, g - 1)
            elif name in _PERMUTES:
                got = nbytes
            else:                      # all_to_all / reduce_scatter
                got = nbytes * (g - 1) // max(1, g)
            tiers[_link_tier(eqn, world, topology)] += got
        elif name == "cond":
            branches = [count_recv_link_bytes(getattr(b, "jaxpr", b),
                                              axis_name, world, topology)
                        for b in eqn.params["branches"]]
            if branches:
                best = max(branches, key=sum)
                tiers = [a + b for a, b in zip(tiers, best)]
        else:
            for sub in _sub_jaxprs_of(eqn):
                sub_t = count_recv_link_bytes(sub, axis_name, world,
                                              topology)
                tiers = [a + b for a, b in zip(tiers, sub_t)]
    return tiers[0], tiers[1], tiers[2]


def pass_wire_reconciliation(traced: TracedGraph) -> List[Finding]:
    """Count the traced graph's per-rank received collective bytes and
    reconcile them against the ``Communicator.recv_wire_bytes`` model that
    telemetry rows and bench projections trust. Fails when the
    hand-maintained model drifts from the real collective schedule by more
    than the documented tolerance (:data:`grace_tpu.core.WIRE_MODEL_RTOL` /
    ``WIRE_MODEL_ATOL``). Needs ``meta['grace']`` (the config bundle) — a
    no-op on traces without a priceable model."""
    from grace_tpu.core import (WIRE_MODEL_ATOL, WIRE_MODEL_RTOL, LinkBytes,
                                negotiation_bytes_for)
    from grace_tpu.transform import (fusion_payload_nbytes,
                                     fusion_payload_structs)
    from grace_tpu.analysis.trace import default_param_structs

    grace = traced.meta.get("grace")
    if grace is None:
        return []
    named = traced.meta.get("param_structs")
    if named is None:
        named = default_param_structs()
    import jax
    leaves = jax.tree_util.tree_leaves(named)

    counted = count_recv_bytes(traced.body, traced.axis_name, traced.world)
    routed = bool(getattr(grace, "routes", None))
    if routed:
        # Routed configs price as the SUM of per-leaf models through each
        # leaf's own codec and communicator (negotiation collectives
        # included) — the one enumeration helper.routed_recv_link_bytes
        # owns, so telemetry, bench, and this audit can never disagree.
        from grace_tpu.helper import routed_recv_link_bytes

        def model_link_at(topo):
            return routed_recv_link_bytes(grace, named, traced.world,
                                          topology=topo)

        model = model_link_at(None).total
        comp_b = None
        comm_name = "routed per-leaf model"
    else:
        _, comp_b, n_elems = fusion_payload_nbytes(
            grace.compressor, leaves, grace.fusion)
        vote = bool(getattr(grace.compressor, "vote_aggregate", False))
        # Negotiation collectives (shared-scale pmax, cyclic Top-K's index
        # broadcast) are real traced bytes — the model must carry them or
        # an index negotiation larger than the atol reads as drift.
        import numpy as _np
        neg_b = sum(count * negotiation_bytes_for(
            grace.compressor,
            int(_np.prod(s.shape, dtype=_np.int64)), traced.world)
            for s, count in fusion_payload_structs(leaves, grace.fusion))

        def model_link_at(topo):
            lb = grace.communicator.recv_link_bytes(
                comp_b, n_elems, traced.world, topology=topo, vote=vote)
            if not neg_b:
                return lb
            # Negotiations are flat full-axis collectives: their bytes
            # land on the worst tier the axis spans (ICI within one
            # slice, DCN across slices, WAN across regions) — same
            # flat_tier rule the telemetry fold uses.
            from grace_tpu.core import Topology as _T
            t = topo if topo is not None else _T()
            tier = t.flat_tier(traced.world)
            return lb._replace(**{tier: getattr(lb, tier) + neg_b})

        model = grace.communicator.recv_wire_bytes(
            comp_b, n_elems, traced.world, vote=vote) + neg_b
        comm_name = f"{type(grace.communicator).__name__}.recv_wire_bytes"
    tol = max(WIRE_MODEL_RTOL * max(model, counted), WIRE_MODEL_ATOL)
    if abs(counted - model) > tol:
        return [Finding(
            pass_name="wire_reconciliation", config=traced.name,
            severity="error", stage="grace/exchange",
            message=(
                f"{comm_name} "
                f"models {model} B/rank/step but the traced graph moves "
                f"{counted} B (world={traced.world}, payload={comp_b} B) — "
                f"drift {abs(counted - model)} B exceeds the documented "
                f"tolerance (rtol={WIRE_MODEL_RTOL}, "
                f"atol={WIRE_MODEL_ATOL} B); telemetry wire_bytes and "
                "bench projections are lying"),
            details=(("model_bytes", int(model)),
                     ("counted_bytes", int(counted)),
                     ("world", traced.world)))]
    # Scalar model reconciles — now hold the per-link breakdown to it.
    # The split (ici, dcn, wan) must sum to the scalar bit-exactly under
    # any topology: a communicator that overrides recv_link_bytes without
    # keeping the identity (or vice versa) would make bench projections
    # price different bytes than telemetry records. Checked at the
    # single-slice default, a slice boundary that forces the DCN leg, and
    # a region boundary that forces the WAN leg.
    from grace_tpu.core import Topology
    half = max(1, traced.world // 2)
    identity_topos = [None, Topology(slice_size=half)]
    if traced.world >= 4:
        identity_topos.append(Topology(slice_size=max(1, traced.world // 4),
                                       region_size=half))
    for topo in identity_topos:
        link = model_link_at(topo)
        if link.total != model:
            return [Finding(
                pass_name="wire_reconciliation", config=traced.name,
                severity="error", stage="grace/exchange",
                message=(
                    f"{comm_name} "
                    f"splits into ici={link.ici} + dcn={link.dcn} + "
                    f"wan={link.wan} = {link.total} B under topology "
                    f"{topo!r}, but the scalar model says {model} B — the "
                    "per-link breakdown and the scalar model must be one "
                    "implementation (override _recv_total_bytes, not the "
                    "public methods)"),
                details=(("model_bytes", int(model)),
                         ("ici_bytes", int(link.ici)),
                         ("dcn_bytes", int(link.dcn)),
                         ("wan_bytes", int(link.wan)),
                         ("world", traced.world)))]
    # Finally reconcile the split itself against the TRACED schedule: put a
    # slice boundary on the audit mesh (the communicator's own slice_size
    # when it declares one — the hierarchical comm's nested sub-axes must
    # land on it — else world/2) and attribute each traced collective's
    # bytes by whether its rank sets cross that boundary. This is what
    # keeps a "mixed" recv_link_bytes honest: a hierarchical communicator
    # whose intra-slice ring secretly crossed slices, or whose DCN leg
    # moved more than the modeled partials, drifts leg-by-leg even when
    # the scalar total still balances.
    own_slice = getattr(grace.communicator, "slice_size", None)
    own_region = getattr(grace.communicator, "region_size", None)
    audit_topo = Topology(
        slice_size=(int(own_slice) if own_slice
                    else max(1, traced.world // 2)),
        region_size=int(own_region) if own_region else None)
    counted_link = count_recv_link_bytes(
        traced.body, traced.axis_name, traced.world, audit_topo)
    model_link = model_link_at(audit_topo)
    for leg, got, want in (("ici", counted_link[0], model_link.ici),
                           ("dcn", counted_link[1], model_link.dcn),
                           ("wan", counted_link[2], model_link.wan)):
        tol = max(WIRE_MODEL_RTOL * max(got, want), WIRE_MODEL_ATOL)
        if abs(got - want) > tol:
            return [Finding(
                pass_name="wire_reconciliation", config=traced.name,
                severity="error", stage="grace/exchange",
                message=(
                    f"{type(grace.communicator).__name__}.recv_link_bytes "
                    f"models {leg}={want} B under topology {audit_topo!r} "
                    f"but the traced schedule moves {got} B over that link "
                    f"class (counted split ici={counted_link[0]}, "
                    f"dcn={counted_link[1]}, wan={counted_link[2]}) — "
                    f"drift {abs(got - want)} B "
                    f"exceeds the documented tolerance "
                    f"(rtol={WIRE_MODEL_RTOL}, atol={WIRE_MODEL_ATOL} B); "
                    "the per-link projections and telemetry split are "
                    "lying about which link the bytes ride"),
                details=(("leg", leg),
                         ("model_ici", int(model_link.ici)),
                         ("model_dcn", int(model_link.dcn)),
                         ("model_wan", int(model_link.wan)),
                         ("counted_ici", int(counted_link[0])),
                         ("counted_dcn", int(counted_link[1])),
                         ("counted_wan", int(counted_link[2])),
                         ("world", traced.world)))]
    return []


# ---------------------------------------------------------------------------
# pass 4: retrace / host-sync sniffing
# ---------------------------------------------------------------------------

def _aval_sig(aval) -> Tuple:
    return (tuple(aval.shape), str(aval.dtype),
            bool(getattr(aval, "weak_type", False)))


def pass_signature_stability(traced: TracedGraph) -> List[Finding]:
    """Two retrace/host-sync smells that turn a compiled step into a
    per-step recompile or a device round-trip:

    * the abstract state signature must be a **fixed point** of the update
      — a weak-type promotion or Python-scalar closure leak (``count +
      1.0``) changes the next step's input avals, forcing jit to retrace
      every step (and silently duplicating compile memory);
    * host callbacks (``io_callback``/``debug_callback``/``pure_callback``)
      inside the compiled step serialize the device against the host —
      telemetry exists precisely so the hot path never does this.
    """
    findings: List[Finding] = []
    for (path, in_aval), (_, out_aval) in zip(traced.state_in,
                                              traced.state_out):
        if _aval_sig(in_aval) != _aval_sig(out_aval):
            si, so = _aval_sig(in_aval), _aval_sig(out_aval)
            what = ("weak-type promotion"
                    if si[:2] == so[:2] and si[2] != so[2]
                    else "abstract-signature change")
            findings.append(Finding(
                pass_name="signature_stability", config=traced.name,
                severity="error",
                message=(
                    f"state leaf '{path}' is not a signature fixed point: "
                    f"in {si[0]}/{si[1]}"
                    f"{'/weak' if si[2] else ''} -> out {so[0]}/{so[1]}"
                    f"{'/weak' if so[2] else ''} ({what} — likely a Python "
                    "scalar leaking into the carried state; jit retraces "
                    "every step)"),
                details=(("path", path),)))

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in _CALLBACK_PRIMS:
                cb = eqn.params.get("callback", "")
                findings.append(Finding(
                    pass_name="signature_stability", config=traced.name,
                    severity="error", stage=_stage_of(eqn),
                    message=(
                        f"host callback '{name}' inside the compiled step "
                        f"({cb!r}) — serializes every step against the "
                        "host; use the in-graph telemetry ring "
                        "(grace_tpu.telemetry) and drain it at flush "
                        "boundaries instead"),
                    details=()))
            for sub in _sub_jaxprs_of(eqn):
                walk(sub)

    walk(traced.body)
    return findings


_PASS_FNS = {
    "collective_consistency": pass_collective_consistency,
    "bit_exactness": pass_bit_exactness,
    "wire_reconciliation": pass_wire_reconciliation,
    "signature_stability": pass_signature_stability,
}


def _resolve_pass(name: str):
    """Pass function by name; loads the graft-flow and graft-sound modules
    on first use of one of their passes (both import this module, so eager
    registration would be a cycle)."""
    fn = _PASS_FNS.get(name)
    if fn is None:
        from grace_tpu.analysis import flow, state_passes
        _PASS_FNS.update(flow.PASS_FNS)
        _PASS_FNS.update(state_passes.PASS_FNS)
        fn = _PASS_FNS[name]
    return fn


def run_passes(traced: TracedGraph,
               passes: Optional[Tuple[str, ...]] = None) -> List[Finding]:
    """Run the named passes (default: all ten) over one traced graph."""
    out: List[Finding] = []
    for name in (passes if passes is not None else PASS_NAMES):
        out.extend(_resolve_pass(name)(traced))
    return out
