"""Finding rendering: terminal text, JSON, and telemetry-compatible JSONL.

The JSONL shape matches what :class:`grace_tpu.telemetry.JSONLSink` writes
— an optional ``{"provenance": ...}`` header line followed by event records
carrying an ``"event"`` key — so ``tools/telemetry_report.py`` renders lint
findings in the same event log as guard trips and consensus repairs, and a
chaos_smoke artifact can carry its lint verdict inline.
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence

from grace_tpu.analysis.passes import Finding

__all__ = ["render_text", "findings_to_json", "write_jsonl", "emit_to_sink"]


def render_text(findings: Sequence[Finding], *, audited: int = 0,
                rules_checked: int = 0) -> str:
    out = []
    errors = [f for f in findings if f.severity == "error"]
    warnings = [f for f in findings if f.severity != "error"]
    for f in findings:
        loc = f.config + (f" [{f.stage}]" if f.stage else "")
        out.append(f"{f.severity.upper():7s} {f.pass_name:24s} {loc}")
        out.append(f"        {f.message}")
    out.append(
        f"graft-lint: {len(errors)} error(s), {len(warnings)} warning(s)"
        + (f" over {audited} config(s)" if audited else "")
        + (f", {rules_checked} repo rule(s)" if rules_checked else ""))
    return "\n".join(out)


def findings_to_json(findings: Sequence[Finding], *, audited: int = 0,
                     rules_checked: int = 0) -> str:
    doc = {
        "tool": "graft_lint",
        "errors": sum(1 for f in findings if f.severity == "error"),
        "warnings": sum(1 for f in findings if f.severity != "error"),
        "configs_audited": audited,
        "rules_checked": rules_checked,
        "findings": [f.as_dict() for f in findings],
    }
    return json.dumps(doc, indent=1)


def write_jsonl(findings: Sequence[Finding], path: str,
                provenance: Optional[dict] = None) -> None:
    """Append findings as ``lint_finding`` events (JSONLSink-compatible)."""
    with open(path, "a") as f:
        if provenance is not None:
            f.write(json.dumps({"provenance": provenance}) + "\n")
        for finding in findings:
            rec = {"event": "lint_finding", **finding.as_dict()}
            f.write(json.dumps(rec) + "\n")


def emit_to_sink(findings: Sequence[Finding], sink) -> None:
    """Write findings into a live telemetry sink (e.g. the chaos_smoke
    JSONL artifact) as ``lint_finding`` events."""
    for finding in findings:
        sink.write({"event": "lint_finding", **finding.as_dict()})
