"""Device-free SPMD tracing: any grace config to a jaxpr on a CPU in CI.

The insight making static auditing possible: ``jax.shard_map`` accepts an
``AbstractMesh`` — a mesh of *names and sizes* with no devices behind it —
and ``jax.make_jaxpr`` happily traces through it. So the full compressed
pipeline (compress, collectives, error feedback, escape cond, consensus
audit) lowers to an inspectable jaxpr at world size W on a machine with one
CPU core and zero TPUs. Collectives appear as first-class equations
(``psum``/``all_gather``/``ppermute``/``all_to_all``), conds carry their
branch jaxprs, and ``jax.named_scope`` stage names from
:mod:`grace_tpu.telemetry.scopes` ride along in each equation's
``source_info.name_stack`` — which is how findings name the offending
pipeline stage.

Rank-variance seeding: inside ``shard_map`` every value is per-device, but
only *some* carry rank-varying data (gradients from the sharded batch,
GraceState mem/comp residuals, telemetry rings); the rest are replicated by
contract (step count, rng key, fallback flag, params). The tracer derives
the seed mask from :func:`grace_tpu.transform.partition_specs` — the same
source of truth the real train step shards state with — so the passes'
replication analysis starts from the layout the system actually promises.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from grace_tpu.core import DEFAULT_AXIS
from grace_tpu.parallel import shard_map
from grace_tpu.transform import MeshSpec, partition_specs

__all__ = ["TracedGraph", "abstract_mesh", "default_param_structs",
           "trace_fn", "trace_update", "trace_train_step"]

# Default parameter tree for config audits. Flat size 512 = 8 * 64: evenly
# shardable over the 8-way audit mesh with shard sizes divisible by 8, so
# bit-packing codecs (signsgd's 8-signs-per-byte) cost the same whether
# packed per shard or whole — keeping the wire-byte reconciliation pass
# free of pure test-shape rounding noise (real gradients are megabytes;
# ceil-rounding on 17-element shards is not a model drift worth flagging).
_DEFAULT_PARAMS = (("w", (60, 8)), ("b", (32,)))


def abstract_mesh(world: int, axis_name: str = DEFAULT_AXIS):
    """An ``AbstractMesh`` across JAX versions (0.4.37 takes one
    ``((name, size), ...)`` tuple; newer releases take separate shape and
    axis-name tuples)."""
    return abstract_mesh_nd(((axis_name, world),))


def abstract_mesh_nd(axes: Sequence[Tuple[str, int]]):
    """N-D ``AbstractMesh`` from ``((name, size), ...)`` pairs — the 2-D
    dp×fsdp audit meshes trace through this."""
    from jax.sharding import AbstractMesh

    axes = tuple((str(n), int(s)) for n, s in axes)
    try:
        return AbstractMesh(axes)
    except (TypeError, ValueError):
        return AbstractMesh(tuple(s for _, s in axes),
                            tuple(n for n, _ in axes))


def default_param_structs() -> Dict[str, jax.ShapeDtypeStruct]:
    return {name: jax.ShapeDtypeStruct(shape, jnp.float32)
            for name, shape in _DEFAULT_PARAMS}


@dataclasses.dataclass
class TracedGraph:
    """One audited program: the shard_map body jaxpr plus audit context.

    ``varying`` maps each body input var to whether it carries rank-varying
    data (the replication-analysis seed). ``state_in``/``state_out`` are
    aligned (path, aval) lists for the optimizer-state portion of the
    signature — the fixed-point check of ``signature_stability``.
    ``grad_in`` lists the body invars carrying gradient (or batch) leaves —
    the dependence-graph layer's bucket roots (:mod:`.flow`); and
    ``state_replicated`` the (path, aval) state leaves whose partition spec
    is ``P()`` — the buffers the memory-footprint pass checks for
    world-scaling shapes. ``meta`` carries whatever the config registry
    wants findings to report (compressor/communicator names, the Grace
    bundle for the wire model).
    """

    name: str
    closed: Any                      # ClosedJaxpr of the whole traced fn
    body: Any                        # the shard_map body Jaxpr
    world: int                       # size of the EXCHANGE (dp) axis
    axis_name: str                   # the exchange (dp) axis name
    varying: Dict[Any, bool]         # dp-axis rank-variance seeds
    state_in: List[Tuple[str, Any]] = dataclasses.field(default_factory=list)
    state_out: List[Tuple[str, Any]] = dataclasses.field(default_factory=list)
    grad_in: List[Any] = dataclasses.field(default_factory=list)
    state_replicated: List[Tuple[str, Any]] = dataclasses.field(
        default_factory=list)
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # 2-D mesh support (dp×fsdp): every mesh axis name in order (empty =
    # 1-D, (axis_name,)), per-axis sizes, and PER-AXIS rank-variance seed
    # maps — a value can be dp-replicated yet fsdp-varying (a param
    # shard), which is exactly what the per-axis replication dataflow of
    # pass 1 distinguishes. Seeded from the same partition_specs contract
    # as ``varying``.
    mesh_axes: Tuple[str, ...] = ()
    axis_sizes: Dict[str, int] = dataclasses.field(default_factory=dict)
    varying_axes: Dict[str, Dict[Any, bool]] = dataclasses.field(
        default_factory=dict)
    # Aligned (path, body var) lists for the state portion of the traced
    # signature — the *var* twins of ``state_in``/``state_out``, recorded so
    # the stateful-semantics passes (graft-sound, :mod:`.state_passes`) can
    # seed per-leaf dataflow from the actual jaxpr vars: rng-lineage roots
    # (the ``rng_key`` leaf), rollback write-sets (every state leaf's
    # input→output pair), and the step-exit replication check. Unlike
    # ``state_in``, these ARE populated for train-step traces (the guard's
    # rollback selects only exist there). ``grace_prefixes`` are the
    # "/"-joined path prefixes of every GraceState node in the traced state
    # tree ("" when the state IS a GraceState), so passes can classify a
    # leaf path into its GraceState field without guessing.
    state_in_vars: List[Tuple[str, Any]] = dataclasses.field(
        default_factory=list)
    state_out_vars: List[Tuple[str, Any]] = dataclasses.field(
        default_factory=list)
    grace_prefixes: Tuple[str, ...] = ()

    @property
    def axes(self) -> Tuple[str, ...]:
        return self.mesh_axes if self.mesh_axes else (self.axis_name,)

    def varying_for(self, axis: str) -> Dict[Any, bool]:
        """Per-axis rank-variance seeds: the recorded per-axis map when
        the tracer produced one, the dp map for the dp axis, else the dp
        map as the conservative stand-in (over-seeding variance can only
        produce false positives, never silent passes)."""
        if axis in self.varying_axes:
            return self.varying_axes[axis]
        return self.varying


def _is_jaxpr_var(v) -> bool:
    return hasattr(v, "aval") and not hasattr(v, "val")


# Sentinels for _seed_positions entries that carry no outer arg index.
_CONST = "const"        # literal / hoisted constant: replicated on every rank
_UNKNOWN = "unknown"    # computed between the inputs and the shard_map


def _seed_positions(closed, n_outer: int):
    """Map each shard_map *body* invar to the outer arg leaf it carries.

    Returns ``(body, positions)`` for the first shard_map equation found
    (depth-first through ``pjit``/``cond``/… wrappers — ``make_train_step``
    jits, so the shard_map usually sits one ``pjit`` down). ``positions``
    has one entry per body invar: the index of the flattened outer
    argument leaf it forwards, :data:`_CONST` for a **hoisted constant** —
    jnp constants created inside the traced step (codec chunk-index
    tables, empty padding arrays, iota ramps) that shard_map lifts into
    extra body invars *ahead of* the real arguments — or :data:`_UNKNOWN`
    for a value computed on the way in. Constants are replicated by
    construction (same bytes on every rank), so seeding them rank-varying
    — which is what a naive positional zip does the moment one appears —
    poisons the whole replication analysis: the escape/audit cond
    predicates read as rank-varying and every legal branch divergence
    becomes a false positive (first seen on the hierarchical
    communicator's chunked Top-K stage-1 encode, whose empty chunk-index
    constants shifted the mask).

    ``positions`` is ``None`` when the shard_map/body arities disagree;
    the whole result is ``None`` when no shard_map equation exists.
    """

    def classify(v, env, jaxpr):
        if not _is_jaxpr_var(v):
            return _CONST
        if v in env:
            return env[v]
        if v in set(getattr(jaxpr, "constvars", ())):
            return _CONST
        return _UNKNOWN

    def walk(jaxpr, env):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "shard_map":
                body = eqn.params["jaxpr"]
                body = getattr(body, "jaxpr", body)
                if len(eqn.invars) != len(body.invars):
                    return body, None
                return body, [classify(v, env, jaxpr) for v in eqn.invars]
            for sub in _sub_jaxprs(eqn):
                ops = (eqn.invars[1:] if eqn.primitive.name == "cond"
                       else eqn.invars)
                if len(sub.invars) == len(ops):
                    sub_env = {sv: classify(ov, env, jaxpr)
                               for sv, ov in zip(sub.invars, ops)}
                else:
                    sub_env = {sv: _UNKNOWN for sv in sub.invars}
                found = walk(sub, sub_env)
                if found is not None:
                    return found
        return None

    env0 = {v: (i if i < n_outer else _UNKNOWN)
            for i, v in enumerate(closed.jaxpr.invars)}
    return walk(closed.jaxpr, env0)


def _seeds_from_positions(positions, mask: List[bool],
                          n_invars: int) -> List[bool]:
    """Rank-variance seed per body invar from a :func:`_seed_positions`
    result: outer leaves take their mask entry, hoisted constants are
    replicated, anything unresolvable is conservatively varying."""
    if positions is None:
        return [True] * n_invars
    return [False if p is _CONST
            else (mask[p] if isinstance(p, int) else True)
            for p in positions]


def _sub_jaxprs(eqn):
    """Every jaxpr nested in an equation's params (cond branches, pjit
    bodies, scan/while jaxprs, custom_*_call), normalized to raw Jaxprs."""
    out = []
    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for item in vs:
            inner = getattr(item, "jaxpr", item)
            if hasattr(inner, "eqns") and hasattr(inner, "invars"):
                out.append(inner)
    return out


def _spec_mentions(spec, axis_name: str) -> bool:
    if spec is None:
        return False
    for entry in spec:
        if entry is None:
            continue
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        if axis_name in names:
            return True
    return False


def _flat_paths(tree) -> List[str]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [_path_str(path) for path, _leaf in flat]


def _path_str(path) -> str:
    parts = []
    for e in path:
        for attr in ("name", "key", "idx"):
            if hasattr(e, attr):
                parts.append(str(getattr(e, attr)))
                break
        else:
            parts.append(str(e))
    return "/".join(parts)


def _grace_prefixes(state_struct) -> Tuple[str, ...]:
    """Path prefixes of every GraceState node embedded in ``state_struct``
    ("" when the state itself is one) — recorded on the TracedGraph so the
    graft-sound passes can map a state leaf path to its GraceState field
    by structure, not by guessing at segment names."""
    from grace_tpu.transform import GraceState

    is_grace = lambda n: isinstance(n, GraceState)          # noqa: E731
    flat, _ = jax.tree_util.tree_flatten_with_path(
        state_struct, is_leaf=is_grace)
    return tuple(_path_str(path) for path, node in flat if is_grace(node))


def _varying_mask_from_specs(state_struct, axis_name: str) -> List[bool]:
    """Per-leaf rank-variance of a state pytree, derived from the same
    ``partition_specs`` the real train step shards it with: leaves whose
    spec mentions the mesh axis (GraceState mem/comp/telem) vary per rank;
    everything else is replicated by the system's own sharding contract."""
    return _varying_masks(state_struct,
                          MeshSpec(dp_axis=axis_name))[axis_name]


def _varying_masks(state_struct, mesh_spec: MeshSpec
                   ) -> Dict[str, List[bool]]:
    """Per-axis per-leaf rank-variance of a state pytree under a (possibly
    2-D) :class:`MeshSpec` — the 2-D replication seeding: a GraceState
    mem leaf (spec ``P((dp, fsdp))``) varies over BOTH axes, a replicated
    field over neither, and the seeding stays derived from the same
    ``partition_specs`` the real train step shards state with."""
    specs = partition_specs(state_struct, mesh_spec)
    flat_specs = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
    flat_state = jax.tree_util.tree_leaves(state_struct)
    if len(flat_specs) != len(flat_state):      # structure drifted — be safe
        return {a: [True] * len(flat_state) for a in mesh_spec.axes}
    return {a: [_spec_mentions(s, a) for s in flat_specs]
            for a in mesh_spec.axes}


def _mesh_of(grace, world: int, fsdp: Optional[int]):
    """Resolve the audit mesh for a config: ``(mesh_spec, axes, dp)``
    where ``axes`` is the ``((name, size), ...)`` AbstractMesh layout and
    ``dp`` the exchange-axis size. A 2-D config (``grace.mesh`` carries
    an fsdp axis, or ``fsdp`` passed explicitly) splits the ``world``
    devices into ``dp = world // fsdp`` exchange groups."""
    mesh_spec = getattr(grace, "mesh", None)
    mesh_spec = MeshSpec.normalize(
        mesh_spec if mesh_spec is not None
        else grace.communicator.axis_name)
    if mesh_spec.is_2d:
        f = int(fsdp) if fsdp else 2
        if world % f:
            raise ValueError(f"fsdp={f} does not divide the audit world "
                             f"{world}")
        dp = world // f
        return mesh_spec, ((mesh_spec.dp_axis, dp),
                           (mesh_spec.fsdp_axis, f)), dp
    return mesh_spec, ((mesh_spec.dp_axis, world),), world


def trace_fn(fn, args: Sequence[Any], *, world: int = 8,
             axis_name: str = DEFAULT_AXIS,
             varying: Optional[Sequence[bool]] = None,
             name: str = "fn", meta: Optional[dict] = None,
             mesh_axes: Optional[Sequence[Tuple[str, int]]] = None,
             varying_axes: Optional[Dict[str, Sequence[bool]]] = None
             ) -> TracedGraph:
    """Trace an arbitrary function inside an AbstractMesh shard_map.

    ``args`` are ShapeDtypeStructs (or arrays) handed to the body
    per-device; ``varying`` flags each *flattened leaf* of ``args`` as
    rank-varying (default: all varying — conservative). This is the
    low-level entry the seeded-bad-graph tests use; config audits go
    through :func:`trace_update` / :func:`trace_train_step`.

    ``mesh_axes`` (``((name, size), ...)``) traces over an N-D mesh
    instead of the 1-D ``(axis_name, world)``; the first axis is the
    exchange axis (``TracedGraph.axis_name``/``world``).
    ``varying_axes`` optionally gives a per-axis mask (defaults to
    ``varying`` for every axis) — how the seeded 2-D replication tests
    express "dp-replicated but fsdp-varying".
    """
    layout = (tuple((str(n), int(s)) for n, s in mesh_axes)
              if mesh_axes is not None else ((axis_name, world),))
    axis_name = layout[0][0]
    world = layout[0][1]
    am = abstract_mesh_nd(layout)
    n_args = len(args)
    sm = shard_map(lambda *a: fn(*a), mesh=am,
                   in_specs=tuple(P() for _ in range(n_args)),
                   out_specs=P(), check_vma=False)
    closed = jax.make_jaxpr(sm)(*args)
    found = _seed_positions(closed, len(jax.tree_util.tree_leaves(
        tuple(args))))
    if found is None:
        raise ValueError("no shard_map equation found in the traced jaxpr")
    body, positions = found
    flat = jax.tree_util.tree_leaves(tuple(args))
    mask = list(varying) if varying is not None else [True] * len(flat)
    if len(mask) != len(flat):
        raise ValueError(f"varying mask has {len(mask)} entries for "
                         f"{len(flat)} flattened arg leaves")
    axis_masks = {a: mask for a, _ in layout}
    if varying_axes:
        for a, m in varying_axes.items():
            m = list(m)
            if len(m) != len(flat):
                raise ValueError(
                    f"varying_axes[{a!r}] has {len(m)} entries for "
                    f"{len(flat)} flattened arg leaves")
            axis_masks[a] = m
    axis_seeds = {a: dict(zip(body.invars, _seeds_from_positions(
        positions, m, len(body.invars))))
        for a, m in axis_masks.items()}
    # Every outer-argument-carrying invar is a dependence root for the
    # low-level entry (the seeded-bad-graph tests treat each arg as one
    # "gradient bucket"); hoisted constants and computed values are not.
    grad_in = ([v for v, p in zip(body.invars, positions)
                if isinstance(p, int)]
               if positions is not None else list(body.invars))
    return TracedGraph(name=name, closed=closed, body=body, world=world,
                       axis_name=axis_name, varying=axis_seeds[axis_name],
                       grad_in=grad_in, meta=dict(meta or {}),
                       mesh_axes=tuple(a for a, _ in layout),
                       axis_sizes={a: s for a, s in layout},
                       varying_axes=axis_seeds)


def trace_update(grace, *, world: int = 8, params=None,
                 name: str = "update", meta: Optional[dict] = None,
                 fsdp: Optional[int] = None) -> TracedGraph:
    """Trace one ``grace_transform`` update (the whole 6-stage pipeline,
    escape cond and telemetry included) at world size ``world``.

    The traced body is exactly what runs inside the real train step's
    shard_map: per-device state in, per-device gradients in, aggregated
    updates and next state out. No devices are touched — state comes from
    ``jax.eval_shape`` over ``init``.

    2-D configs (``grace.mesh`` carries an fsdp axis, or ``fsdp`` given)
    trace over a dp×fsdp AbstractMesh of the same ``world`` devices
    (``dp = world // fsdp``): the gradients seed rank-varying over BOTH
    axes (each device holds its own shard's local gradient), GraceState
    leaves seed from the 2-D ``partition_specs``, and ``TracedGraph.world``
    becomes the dp size — the span every wire/numeric model prices.
    """
    axis_name = grace.communicator.axis_name
    mesh_spec, mesh_axes, dp = _mesh_of(grace, world, fsdp)
    tx = grace.transform(seed=0)
    params = params if params is not None else default_param_structs()
    state_struct = jax.eval_shape(tx.init, params)
    grads_struct = jax.tree_util.tree_map(
        lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), params)

    def body(state, grads):
        updates, new_state = tx.update(grads, state, None)
        return updates, new_state

    am = abstract_mesh_nd(mesh_axes)
    sm = shard_map(body, mesh=am, in_specs=(P(), P()),
                   out_specs=(P(), P()), check_vma=False)
    closed = jax.make_jaxpr(sm)(state_struct, grads_struct)
    state_flat = jax.tree_util.tree_leaves(state_struct)
    grads_flat = jax.tree_util.tree_leaves(grads_struct)
    found = _seed_positions(closed, len(state_flat) + len(grads_flat))
    if found is None:
        raise ValueError("no shard_map equation found in the traced update")
    inner, positions = found

    masks = _varying_masks(state_struct, mesh_spec)
    axis_seeds = {}
    for a in mesh_spec.axes:
        mask_a = masks[a] + [True] * len(grads_flat)
        axis_seeds[a] = dict(zip(inner.invars, _seeds_from_positions(
            positions, mask_a, len(inner.invars))))
    state_in = []
    state_in_vars = []
    grad_in = []
    if positions is not None:
        # Body invar carrying outer arg leaf i (hoisted constants shift
        # the real arguments, so positional zip is not enough).
        arg_to_body = {i: p for p, i in enumerate(positions)
                       if isinstance(i, int)}
        paths = _flat_paths(state_struct)
        state_in_vars = [(p, inner.invars[arg_to_body[i]])
                         for i, p in enumerate(paths)
                         if i in arg_to_body]
        if len(state_in_vars) != len(paths):     # a state leaf went missing
            state_in_vars = []
        state_in = [(p, v.aval) for p, v in state_in_vars]
        grad_in = [inner.invars[b] for i, b in sorted(arg_to_body.items())
                   if i >= len(state_flat)]
    # Replicated-by-contract state leaves (spec P() — replicated over
    # EVERY mesh axis): the buffers the memory-footprint pass checks for
    # world-scaling shapes.
    state_replicated = [
        (p, a) for i, (p, a) in enumerate(state_in)
        if not any(masks[ax][i] for ax in mesh_spec.axes)]

    # Body outputs are (updates..., new_state...): the state signature the
    # next step re-traces against is the trailing slice.
    n_state = len(state_flat)
    state_out = []
    state_out_vars = []
    if state_in and len(inner.outvars) >= n_state:
        out_tail = inner.outvars[len(inner.outvars) - n_state:]
        state_out_vars = [(p, v) for (p, _), v in zip(state_in, out_tail)]
        state_out = [(p, v.aval)
                     for (p, _), v in zip(state_in, out_tail)]
    return TracedGraph(name=name, closed=closed, body=inner, world=dp,
                       axis_name=axis_name,
                       varying=axis_seeds[mesh_spec.dp_axis],
                       state_in=state_in, state_out=state_out,
                       grad_in=grad_in, state_replicated=state_replicated,
                       meta=dict(meta or {}),
                       mesh_axes=tuple(mesh_spec.axes),
                       axis_sizes={n: s for n, s in mesh_axes},
                       varying_axes=axis_seeds,
                       state_in_vars=state_in_vars,
                       state_out_vars=state_out_vars,
                       grace_prefixes=_grace_prefixes(state_struct))


def trace_train_step(grace, *, world: int = 8, guard: Optional[dict] = None,
                     consensus=None, name: str = "train_step",
                     meta: Optional[dict] = None,
                     fsdp: Optional[int] = None) -> TracedGraph:
    """Trace a full ``make_train_step`` program (fwd/bwd, optimizer chain,
    optional guard and consensus audit) over an AbstractMesh.

    This is the graph the collective-consistency and bit-exactness passes
    care most about: the guard's skip/rollback selects, the dense-escape
    cond, and the consensus ``lax.cond`` audit gate with its fingerprint
    all_gather and masked-psum repair broadcasts all appear here exactly as
    they would on a pod.
    """
    from grace_tpu.train import TrainState, make_train_step
    from grace_tpu.transform import add_world_axis

    axis_name = grace.communicator.axis_name
    mesh_spec, mesh_axes, dp = _mesh_of(grace, world, fsdp)
    params = default_param_structs()
    dim, classes = _DEFAULT_PARAMS[0][1][0], _DEFAULT_PARAMS[0][1][1]

    def loss_fn(p, batch):
        x, y = batch
        logits = x @ p["w"] + p["b"][:classes]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    tx = optax.chain(grace.transform(seed=0), optax.sgd(0.1))
    if guard is not None:
        from grace_tpu.resilience import guard_transform
        guard_axes = (tuple(mesh_spec.axes) if mesh_spec.is_2d
                      else axis_name)
        tx = guard_transform(tx, axis_name=guard_axes, **guard)

    am = abstract_mesh_nd(mesh_axes)
    abstract = jax.eval_shape(tx.init, params)
    specs = partition_specs(abstract, mesh_spec)
    init_fn = shard_map(lambda p: add_world_axis(tx.init(p)), mesh=am,
                        in_specs=(P(),), out_specs=specs, check_vma=False)
    opt_struct = jax.eval_shape(init_fn, params)
    state_struct = TrainState(params=params, opt_state=opt_struct)
    batch = (jax.ShapeDtypeStruct((dp * 4, dim), jnp.float32),
             jax.ShapeDtypeStruct((dp * 4,), jnp.int32))

    step = make_train_step(loss_fn, tx, mesh=am, axis_name=mesh_spec,
                           donate=False, consensus=consensus)
    closed = jax.make_jaxpr(step)(state_struct, batch)
    state_flat = jax.tree_util.tree_leaves(state_struct)
    batch_flat = jax.tree_util.tree_leaves(batch)
    found = _seed_positions(closed, len(state_flat) + len(batch_flat))
    if found is None:
        raise ValueError("no shard_map equation found in the traced step")
    inner, positions = found

    masks = _varying_masks(state_struct, mesh_spec)
    axis_seeds = {}
    for a in mesh_spec.axes:
        mask_a = masks[a] + [True] * len(batch_flat)
        axis_seeds[a] = dict(zip(inner.invars, _seeds_from_positions(
            positions, mask_a, len(inner.invars))))
    grad_in = []
    state_in_vars = []
    state_out_vars = []
    if positions is not None:
        arg_to_body = {i: p for p, i in enumerate(positions)
                       if isinstance(i, int)}
        grad_in = [inner.invars[b] for i, b in sorted(arg_to_body.items())
                   if i >= len(state_flat)]
        # The step returns (TrainState, loss): the flattened outputs lead
        # with the state leaves in the same path order the inputs carry.
        paths = _flat_paths(state_struct)
        state_in_vars = [(p, inner.invars[arg_to_body[i]])
                         for i, p in enumerate(paths) if i in arg_to_body]
        if len(state_in_vars) != len(paths):
            state_in_vars = []
        elif len(inner.outvars) >= len(paths):
            state_out_vars = list(zip(paths, inner.outvars[:len(paths)]))
    meta = dict(meta or {})
    meta.setdefault("guard", guard)
    meta.setdefault("consensus", consensus)
    return TracedGraph(name=name, closed=closed, body=inner, world=dp,
                       axis_name=axis_name,
                       varying=axis_seeds[mesh_spec.dp_axis],
                       grad_in=grad_in, meta=meta,
                       mesh_axes=tuple(mesh_spec.axes),
                       axis_sizes={n: s for n, s in mesh_axes},
                       varying_axes=axis_seeds,
                       state_in_vars=state_in_vars,
                       state_out_vars=state_out_vars,
                       grace_prefixes=_grace_prefixes(state_struct))
